import ray_tpu
from ray_tpu import data as rd

ray_tpu.init(num_cpus=4)
ds = rd.range(16, parallelism=2).random_shuffle(seed=7)
print("vals:", sorted(ds.take_all()))
ray_tpu.shutdown()
print("OK")
