"""@ray_tpu.remote for functions.

Reference: python/ray/remote_function.py:35 RemoteFunction with _remote
(:231) resolving options and submitting through the core worker.
"""

from __future__ import annotations

import functools

from ray_tpu._private import worker as worker_mod


class RemoteFunction:
    def __init__(self, fn, **default_opts):
        self._function = fn
        self._default_opts = default_opts
        self._fn_id = None
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._function.__name__}' cannot be called "
            f"directly. Use '{self._function.__name__}.remote()'.")

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_opts)

    def options(self, **opts):
        merged = {**self._default_opts, **opts}
        parent = self

        class _Optioned:
            def remote(self, *args, **kwargs):
                return parent._remote(args, kwargs, merged)

        return _Optioned()

    def _remote(self, args, kwargs, opts):
        w = worker_mod.global_worker
        if w is None or not w.connected:
            raise RuntimeError("ray_tpu.init() must be called first")
        if self._fn_id is None or self._exported_by is not w:
            self._fn_id = w.export_function(self._function)
            self._exported_by = w
        num_returns = opts.get("num_returns", 1)
        refs = w.submit_task(self._fn_id, args, kwargs, dict(opts))
        if num_returns == 1 or num_returns == "dynamic":
            # "dynamic": ray_tpu.get(ref) yields an ObjectRefGenerator
            # over the task generator's per-item refs (reference:
            # num_returns="dynamic" tasks).
            return refs[0]
        return refs

    _exported_by = None

    def __getstate__(self):
        # Export caches hold the CoreWorker (unpicklable mmap); a pickled
        # RemoteFunction re-exports lazily in the destination process.
        state = self.__dict__.copy()
        state["_fn_id"] = None
        state.pop("_exported_by", None)
        return state

    @property
    def bind(self):
        from ray_tpu.dag import FunctionNode

        def _bind(*args, **kwargs):
            return FunctionNode(self._function, args, kwargs,
                                self._default_opts)
        return _bind
