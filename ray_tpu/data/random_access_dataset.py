"""Distributed random (point) access over a sorted Dataset.

Reference: python/ray/data/random_access_dataset.py (RandomAccessDataset,
_RandomAccessWorker): sort by key, record per-block [min, max] bounds,
spread worker actors each pinning a subset of blocks, route each lookup
to a worker holding the covering block via binary search on the bounds.

Design notes vs the reference: same architecture (sorted blocks +
bounds index + worker actors), but lookups inside a worker use numpy
searchsorted on a cached key column instead of per-row scans, and each
worker gets a CONTIGUOUS chunk of the sorted block list
(np.array_split sizing) so its blocks are adjacent in key space and
batch multigets over nearby keys mostly hit one worker.
"""

from __future__ import annotations

import bisect
import collections
import random
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_GET_TIMEOUT = 600.0


def _block_bounds(block, key: str):
    from ray_tpu.data.block import BlockAccessor
    acc = BlockAccessor(block)
    if acc.num_rows() == 0:
        return None
    col = np.asarray(acc.to_numpy(key))
    return (col[0].item(), col[-1].item())


class _RandomAccessWorker:
    """Holds a subset of sorted blocks; answers point lookups."""

    def __init__(self, key: str):
        self._key = key
        self._blocks: Dict[int, Any] = {}
        self._keys: Dict[int, np.ndarray] = {}
        self._num_queries = 0

    def assign(self, idxs: List[int], *blocks) -> int:
        """Blocks arrive as TOP-LEVEL task args (ObjectRefs nested in
        containers deliberately don't auto-resolve — reference
        semantics), so the runtime hands this method materialized
        blocks."""
        from ray_tpu.data.block import BlockAccessor
        for i, b in zip(idxs, blocks):
            self._blocks[i] = b
            self._keys[i] = np.asarray(BlockAccessor(b).to_numpy(self._key))
        return len(self._blocks)

    def get(self, block_idx: int, key_val) -> Optional[dict]:
        self._num_queries += 1
        keys = self._keys.get(block_idx)
        if keys is None or keys.size == 0:
            return None
        j = int(np.searchsorted(keys, key_val))
        if j >= keys.size or keys[j] != key_val:
            return None
        from ray_tpu.data.block import BlockAccessor
        return BlockAccessor(
            BlockAccessor(self._blocks[block_idx]).slice(j, j + 1)
        ).to_pylist()[0]

    def multiget(self, block_idxs: List[int], key_vals: List[Any]
                 ) -> List[Optional[dict]]:
        return [self.get(i, k) for i, k in zip(block_idxs, key_vals)]

    def stats(self) -> dict:
        return {"blocks": len(self._blocks),
                "num_queries": self._num_queries}


class RandomAccessDataset:
    def __init__(self, dataset, key: str, num_workers: int = 2):
        sorted_ds = dataset.sort(key)
        refs = sorted_ds.get_internal_block_refs()
        bounds_task = ray_tpu.remote(_block_bounds)
        bounds = ray_tpu.get([bounds_task.remote(b, key) for b in refs],
                             timeout=_GET_TIMEOUT)
        self._key = key
        self._non_empty: List = []
        self._upper_bounds: List = []
        for ref, b in zip(refs, bounds):
            if b is not None:
                self._non_empty.append(ref)
                self._upper_bounds.append(b[1])

        n = max(1, min(num_workers, len(self._non_empty) or 1))
        worker_cls = ray_tpu.remote(_RandomAccessWorker)
        self._workers = [worker_cls.remote(key) for _ in range(n)]
        self._block_to_worker: Dict[int, int] = {}
        assign: List[Dict[int, Any]] = [{} for _ in range(n)]
        # Contiguous chunk per worker (round-robin would interleave the
        # sorted sequence and scatter adjacent keys across workers).
        for w, idxs in enumerate(
                np.array_split(np.arange(len(self._non_empty)), n)):
            for i in idxs:
                i = int(i)
                self._block_to_worker[i] = w
                assign[w][i] = self._non_empty[i]
        ray_tpu.get([w.assign.remote(list(a.keys()), *a.values())
                     for w, a in zip(self._workers, assign) if a],
                    timeout=_GET_TIMEOUT)

    def _locate(self, key_val) -> Optional[int]:
        i = bisect.bisect_left(self._upper_bounds, key_val)
        return i if i < len(self._non_empty) else None

    def get_async(self, key_val):
        """ObjectRef resolving to the row with sort-key == key_val, or
        None if absent."""
        i = self._locate(key_val)
        if i is None:
            return ray_tpu.put(None)
        w = self._workers[self._block_to_worker[i]]
        return w.get.remote(i, key_val)

    def multiget(self, keys: List[Any]) -> List[Optional[dict]]:
        """Batched lookup: keys are grouped per worker so each worker
        answers its whole batch in one RPC."""
        per_worker: Dict[int, List] = collections.defaultdict(list)
        order: List = [None] * len(keys)
        misses: List[int] = []
        for pos, k in enumerate(keys):
            i = self._locate(k)
            if i is None:
                misses.append(pos)
            else:
                per_worker[self._block_to_worker[i]].append((pos, i, k))
        widxs = list(per_worker)
        futs = [self._workers[w].multiget.remote(
                    [t[1] for t in per_worker[w]],
                    [t[2] for t in per_worker[w]]) for w in widxs]
        # One batched get: fetching inside the loop would serialize on
        # the slowest earlier worker (our own lint rule RTL001).
        for widx, rows in zip(widxs,
                              ray_tpu.get(futs, timeout=_GET_TIMEOUT)):
            for (pos, _, _), row in zip(per_worker[widx], rows):
                order[pos] = row
        return order

    def stats(self) -> str:
        st = ray_tpu.get([w.stats.remote() for w in self._workers],
                         timeout=_GET_TIMEOUT)
        lines = ["RandomAccessDataset:"]
        for i, s in enumerate(st):
            lines.append(f"  worker {i}: {s['blocks']} blocks, "
                         f"{s['num_queries']} queries")
        return "\n".join(lines)

    def __del__(self):
        try:
            for w in getattr(self, "_workers", []):
                ray_tpu.kill(w)
        except Exception:
            pass
