"""Dataset: lazy, distributed, block-based data transforms.

Reference: python/ray/data/dataset.py:124 (map_batches :300), _internal/
plan.py:69 (ExecutionPlan of stages), _internal/compute.py (TaskPool vs
ActorPool strategies), _internal/push_based_shuffle.py (all-to-all).

Design: a Dataset is (block_refs, lazy stage list).  Stages are per-block
transforms executed as tasks (one task per block, full parallelism) or on
a reusable actor pool (expensive per-actor setup, e.g. a jax model for
batch inference).  All-to-all ops (shuffle/sort/groupby) run a two-round
task graph: partition each block -> combine each partition.
"""

from __future__ import annotations

import builtins
import random
from typing import Any, Callable, Dict, Iterable, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu.data.block import BlockAccessor


def _get_timeout() -> float:
    """One deadline for every data-layer get/wait
    (``RT_DATA_GET_TIMEOUT_S``; was a hardcoded 600 s constant)."""
    return cfg.data_get_timeout_s


class DataContext:
    """Process-wide data-layer knobs (reference: DatasetContext).

    target_max_block_size bounds materialized block sizes: oversized
    stage outputs are split by row-range tasks (reference: dynamic block
    splitting in _internal/block_list mutations).  target_shuffle_rounds
    controls the push-based shuffle's map/merge overlap."""

    target_max_block_size: Optional[int] = 128 * 1024 * 1024
    target_shuffle_rounds: int = 4

    _instance = None

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance


# --------------------------------------------------------------------------
# compute strategies


class TaskPoolStrategy:
    """One task per block (reference: _internal/compute.py:56)."""


class ActorPoolStrategy:
    """A pool of long-lived transform actors (reference: compute.py:146).
    Use for stateful/expensive-setup UDFs (model inference on TPU
    replicas)."""

    def __init__(self, size: int = 2, min_size: int = 0, max_size: int = 0):
        self.size = max(size, min_size) or 2


class _TransformActor:
    def __init__(self, fn_factory):
        self._fn = fn_factory() if fn_factory else None

    def apply(self, fn_or_none, block, fn_args, fn_kwargs):
        fn = fn_or_none if fn_or_none is not None else self._fn
        return fn(block, *fn_args, **fn_kwargs)


def _apply_stage_task(fn, block, fn_args, fn_kwargs):
    return fn(block, *fn_args, **fn_kwargs)


def _accum_blocks(*blocks):
    return BlockAccessor.combine(list(blocks))


def _push_shuffle(refs: List, partition_fn: Callable, n_out: int) -> List:
    """Pipelined all-to-all core (reference: push_based_shuffle.py:330).

    Map tasks (`partition_fn(block, idx) -> n_out partitions`) are
    launched in rounds; after each round, per-output accumulator tasks
    fold that round's partitions into a running block.  Because the
    accumulators only depend on their round's maps, they execute while
    later rounds' maps are still running — map/merge overlap instead of
    a global barrier — and peak memory per merge is one round's
    partitions, not the whole dataset's."""
    if not refs:
        return []
    rounds = max(1, DataContext.get_current().target_shuffle_rounds)
    round_size = max(1, (len(refs) + rounds - 1) // rounds)
    if n_out == 1:
        # num_returns=1 stores the 1-element partition LIST as the
        # object's value; the accumulator would then concatenate
        # block-LISTS as rows.  Unwrap at the source (same guard as
        # the streaming exchange).
        _multi = partition_fn

        def partition_fn(block, idx, _multi=_multi):  # noqa: F811
            return _multi(block, idx)[0]
    part_task = ray_tpu.remote(partition_fn).options(num_returns=n_out)
    accum = ray_tpu.remote(_accum_blocks)
    acc_refs: List = [None] * n_out
    for r0 in range(0, len(refs), round_size):
        chunk = refs[r0:r0 + round_size]
        parts = [part_task.remote(b, r0 + i) for i, b in enumerate(chunk)]
        if n_out == 1:
            parts = [[p] for p in parts]
        for i in range(n_out):
            cols = [parts[b][i] for b in range(len(parts))]
            prev = [] if acc_refs[i] is None else [acc_refs[i]]
            acc_refs[i] = accum.remote(*prev, *cols)
    return acc_refs


# --------------------------------------------------------------------------


class Dataset:
    def __init__(self, block_refs: List, stages: Optional[List] = None,
                 stats: Optional[List] = None,
                 input_files: Optional[List[str]] = None):
        self._block_refs = list(block_refs)
        self._stages = list(stages or [])
        # Per-stage execution records (reference: data/_internal/stats.py
        # DatasetStats): [{"stage", "blocks", "wall_s"}].
        self._stats = list(stats or [])
        # Source files, when created by a file reader (reference:
        # Dataset.input_files over the lazy block list's read tasks).
        self._input_files = list(input_files or [])

    # ---------------------------------------------------------------- plan
    def _with_stage(self, fn: Callable, compute=None, fn_args=(),
                    fn_kwargs=None) -> "Dataset":
        return Dataset(self._block_refs,
                       self._stages + [(fn, compute, fn_args,
                                        fn_kwargs or {})],
                       stats=self._stats,
                       input_files=self._input_files)

    @staticmethod
    def _fuse(stages):
        """One callable running the whole stage chain on a block (the
        reference's stage fusion) — shared by the materializing and
        streaming executors."""
        def _fused(block):
            for fn, _, fn_args, fn_kwargs in stages:
                block = fn(block, *fn_args, **fn_kwargs)
            return block
        return _fused

    def _execute(self) -> List:
        """Materialize all stages -> block refs, segment-wise: fusable
        map runs execute as one task per block (or one actor-pool pass),
        all-to-all markers (streaming mode's lazy shuffle) run the
        transfer-plane exchange."""
        if not self._stages:
            return self._block_refs
        from ray_tpu.data._internal.operators import split_segments
        import time as _time
        # Pop-on-success throughout: a failed exchange (node death past
        # the deadline) or a raising actor-pool segment must leave its
        # stages pending, not silently yield the untransformed input to
        # a retrying caller.
        for kind, seg in split_segments(list(self._stages)):
            if kind == "all_to_all":
                from ray_tpu.data._internal.shuffle import exchange_bulk
                t0 = _time.perf_counter()
                self._block_refs = exchange_bulk(self._block_refs, seg)
                del self._stages[:1]
                self._stats.append({"stage": seg.__name__,
                                    "blocks": len(self._block_refs),
                                    "wall_s": _time.perf_counter() - t0})
            else:
                self._run_map_segment(seg)
                del self._stages[:len(seg)]
        return self._block_refs

    def _run_map_segment(self, stages) -> None:
        """One fused map run over every block (the pre-operator-graph
        _execute body, now per segment)."""
        import time as _time
        t0 = _time.perf_counter()
        stage_names = "+".join(
            getattr(s[0], "__name__", "stage").lstrip("_")
            for s in stages)
        _fused = self._fuse(stages)

        actor_stages = [s for s in stages
                        if isinstance(s[1], ActorPoolStrategy)]
        if actor_stages:
            pool_size = max(s[1].size for s in actor_stages)
            actor_cls = ray_tpu.remote(_TransformActor)
            pool = [actor_cls.remote(None) for _ in range(pool_size)]
            refs = []
            for i, b in enumerate(self._block_refs):
                actor = pool[i % pool_size]
                refs.append(actor.apply.remote(_fused, b, (), {}))
            out = ray_tpu.get(refs, timeout=_get_timeout())
            blocks = [ray_tpu.put(b) for b in out]
            for a in pool:
                ray_tpu.kill(a)
        else:
            task = ray_tpu.remote(_apply_stage_task)
            blocks = [task.remote(_fused, b, (), {})
                      for b in self._block_refs]
        self._block_refs = blocks
        self._stats.append({"stage": stage_names,
                            "blocks": len(blocks),
                            "wall_s": _time.perf_counter() - t0})

    def stats(self) -> str:
        """Human-readable per-stage execution summary (reference:
        Dataset.stats / _internal/stats.py)."""
        if not self._stats:
            return "(no stages executed yet)"
        lines = []
        for s in self._stats:
            lines.append(f"Stage {s['stage']}: {s['blocks']} blocks "
                         f"submitted in {s['wall_s']:.3f}s")
        return "\n".join(lines)

    def materialize(self) -> "Dataset":
        self._execute()
        # Force completion so downstream count() etc. are cheap.
        ray_tpu.wait(self._block_refs, num_returns=len(self._block_refs),
                     timeout=_get_timeout())
        self._enforce_block_size()
        return self

    def _enforce_block_size(self, target: Optional[int] = None):
        """Dynamic block splitting (reference: dynamic block splitting by
        target_max_block_size): any materialized block over the target is
        split into row-range sub-blocks by a task where it lives.  The
        driver sees only sizes, never bytes."""
        target = target or DataContext.get_current().target_max_block_size
        if not target or not self._block_refs:
            return

        def _size(block):
            return BlockAccessor(block).size_bytes()

        size_task = ray_tpu.remote(_size)
        sizes = ray_tpu.get([size_task.remote(b) for b in self._block_refs],
                            timeout=_get_timeout())
        if all(s <= target for s in sizes):
            return

        def _split(block, pieces):
            acc = BlockAccessor(block)
            rows = acc.num_rows()
            per = (rows + pieces - 1) // pieces
            out = []
            for i in range(pieces):
                lo = min(rows, i * per)
                hi = min(rows, (i + 1) * per)
                out.append(acc.slice(lo, max(lo, hi)))
            return out

        new_refs: List = []
        for ref, size in zip(self._block_refs, sizes):
            if size <= target:
                new_refs.append(ref)
                continue
            pieces = int(-(-size // target))
            split = ray_tpu.remote(_split).options(num_returns=pieces)
            out = split.remote(ref, pieces)
            new_refs.extend(out if isinstance(out, list) else [out])
        self._block_refs = new_refs

    def _blocks(self) -> List:
        """Materialized local blocks."""
        return ray_tpu.get(self._execute(), timeout=_get_timeout())

    def _iter_local_blocks(self, max_in_flight: int = 4) -> Iterable:
        """Streaming block iterator (reference: the streaming executor
        that replaced bulk execution as Data's default consume path).

        With pending task-compatible stages, blocks are transformed by
        a bounded sliding window of tasks and yielded in order — peak
        local memory is O(max_in_flight blocks), and the first batch is
        ready after one block's latency.  Falls back to materializing
        for actor-pool stages (the pool amortizes setup over ALL
        blocks) or when already materialized.  Streaming does not cache
        stage outputs: re-iterating re-executes the chain.
        """
        from ray_tpu.data._internal.operators import AllToAllOp
        if self._stages and not any(
                isinstance(s[1], ActorPoolStrategy) for s in self._stages):
            if cfg.data_streaming:
                # Operator-graph executor: fused map operators with
                # output budgets + pull backpressure; all-to-all
                # markers stream through the transfer-plane exchange.
                from ray_tpu.data._internal.streaming_executor import (
                    StreamingExecutor)
                yield from StreamingExecutor(
                    self._block_refs, self._stages).iter_blocks()
                return
            if not any(isinstance(s[0], AllToAllOp)
                       for s in self._stages):
                # Legacy bounded-window map loop (RT_DATA_STREAMING=0
                # — bench baseline).  A pended all-to-all marker (the
                # knob was flipped between creation and consumption)
                # cannot be fused as a map fn; it falls through to
                # _execute(), which runs it segment-wise.
                from ray_tpu.data.streaming import StreamingExecutor
                yield from StreamingExecutor(
                    self._block_refs, self._fuse(self._stages),
                    max_in_flight=max_in_flight).iter_blocks()
                return
        for ref in self._execute():
            yield ray_tpu.get(ref, timeout=_get_timeout())

    # ---------------------------------------------------------- transforms
    def map_batches(self, fn: Callable, *, batch_format: Optional[str] =
                    "numpy", compute=None, fn_args=(), fn_kwargs=None,
                    batch_size: Optional[int] = None, **_ignored
                    ) -> "Dataset":
        """Apply fn to whole blocks (reference: dataset.py:300)."""
        def _map_batches(block, *args, **kwargs):
            acc = BlockAccessor(block)
            batch = acc.to_batch_format(batch_format)
            out = fn(batch, *args, **kwargs)
            return out

        return self._with_stage(_map_batches, compute, fn_args, fn_kwargs)

    def map(self, fn: Callable, compute=None) -> "Dataset":
        def _map(block):
            rows = BlockAccessor(block).to_pylist()
            return [fn(r) for r in rows]
        return self._with_stage(_map, compute)

    def flat_map(self, fn: Callable, compute=None) -> "Dataset":
        def _flat(block):
            rows = BlockAccessor(block).to_pylist()
            out = []
            for r in rows:
                out.extend(fn(r))
            return out
        return self._with_stage(_flat, compute)

    def filter(self, fn: Callable, compute=None) -> "Dataset":
        def _filter(block):
            rows = BlockAccessor(block).to_pylist()
            return [r for r in rows if fn(r)]
        return self._with_stage(_filter, compute)

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def _add(block):
            df = BlockAccessor(block).to_pandas().copy()
            df[name] = fn(df)
            return df
        return self._with_stage(_add, None)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def _drop(block):
            return BlockAccessor(block).to_pandas().drop(columns=cols)
        return self._with_stage(_drop, None)

    def select_columns(self, cols: List[str]) -> "Dataset":
        def _sel(block):
            return BlockAccessor(block).to_pandas()[cols]
        return self._with_stage(_sel, None)

    # ------------------------------------------------------------- shuffle
    def repartition(self, num_blocks: int) -> "Dataset":
        """Distributed repartition: every block is sliced into per-output
        row ranges by a task where the block LIVES, and each output is
        assembled by a merge task — no block ever rides through the
        driver (the driver only sees row counts).  In streaming mode
        the merge runs through the transfer-plane exchange (windowed,
        locality-placed); legacy two-round graph kept as baseline."""
        refs = self._execute()
        num_blocks = max(1, num_blocks)
        if not refs:
            return Dataset([ray_tpu.put([]) for _ in range(num_blocks)])
        if cfg.data_streaming:
            from ray_tpu.data._internal.shuffle import exchange_bulk
            return Dataset(exchange_bulk(refs,
                                         _repartition_op(num_blocks)))
        if num_blocks == 1:
            # One merge task; the slice graph's num_returns=1 path
            # would nest the 1-element slice LIST as the block value.
            one = ray_tpu.remote(_accum_blocks)
            return Dataset([one.remote(*refs)])

        def _rows(block):
            return BlockAccessor(block).num_rows()

        rows_task = ray_tpu.remote(_rows)
        counts = ray_tpu.get([rows_task.remote(b) for b in refs],
                             timeout=_get_timeout())
        total = sum(counts)
        per = (total + num_blocks - 1) // num_blocks
        # Global row ranges -> per-input slice lists.
        starts = np.cumsum([0] + counts)

        def _slices(block, first_row):
            acc = BlockAccessor(block)
            rows = acc.num_rows()
            out = []
            for j in range(num_blocks):
                lo = max(0, j * per - first_row)
                hi = min(rows, (j + 1) * per - first_row)
                out.append(acc.slice(lo, max(lo, hi)))
            return out

        slice_task = ray_tpu.remote(_slices).options(
            num_returns=num_blocks)
        parts = [slice_task.remote(b, int(starts[i]))
                 for i, b in enumerate(refs)]
        if num_blocks == 1:
            parts = [[p] for p in parts]

        def _cat(*chunks):
            return BlockAccessor.combine(list(chunks))

        cat = ray_tpu.remote(_cat)
        return Dataset([cat.remote(*[parts[i][j] for i in range(len(parts))])
                        for j in range(num_blocks)])

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Random row shuffle.

        Seeded shuffles are DETERMINISTIC for a fixed seed regardless
        of parallelism or round structure: every per-block RNG derives
        from (seed, block_index) and every output permutation from
        (seed, output_index) — never from round interleaving — so the
        streaming and legacy executors produce byte-identical results
        (required for reproducible train ingest; regression-tested).

        Streaming mode (RT_DATA_STREAMING=1): the shuffle PENDS as an
        all-to-all stage and runs through the transfer-plane exchange
        when consumed — partitions move once, windowed, pulled by
        reduce tasks placed where most of their bytes live.  Legacy
        mode keeps the push-based ROUND graph (map rounds folded into
        per-output accumulators; reference:
        _internal/push_based_shuffle.py:330) as the bench baseline."""
        seed = seed if seed is not None else random.randrange(1 << 30)
        if cfg.data_streaming:
            return Dataset(
                self._block_refs,
                self._stages + [(_random_shuffle_op(seed), None, (), {})],
                stats=self._stats, input_files=self._input_files)
        refs = self._execute()
        n_out = len(refs) or 1

        def _partition(block, idx):
            return _shuffle_partition_rows(block, idx, seed, n_out)

        def _finalize(block, out_idx):
            return _shuffle_finalize_rows(block, seed, out_idx)

        out = _push_shuffle(refs, _partition, n_out)
        fin = ray_tpu.remote(_finalize)
        return Dataset([fin.remote(b, i) for i, b in enumerate(out)])

    def sort(self, key: Optional[str] = None, descending: bool = False
             ) -> "Dataset":
        """Distributed sample-partition-sort (reference: data/_internal/
        sort.py — sample keys per block, compute range boundaries,
        range-partition every block, sort each range independently).  No
        block ever rides through the driver; output block j holds range j
        so concatenating the blocks in order is globally sorted."""
        refs = self._execute()
        n = len(refs) or 1

        def _sort_one(block):
            return _local_sort(block, key, descending)

        if n == 1:
            one = ray_tpu.remote(_sort_one)
            return Dataset([one.remote(refs[0])])

        def _sample(block):
            vals = _key_values(block, key)
            rows = len(vals)
            if rows == 0:
                return vals
            idxs = np.random.RandomState(0).randint(
                0, rows, size=min(32, rows))
            return vals[idxs]

        sample_task = ray_tpu.remote(_sample)
        samples = ray_tpu.get([sample_task.remote(b) for b in refs],
                              timeout=_get_timeout())
        merged = np.sort(np.concatenate(
            [s for s in samples if len(s)] or [np.array([])]))
        if len(merged) == 0:
            return Dataset(refs)
        boundaries = np.array(
            [merged[int(len(merged) * i / n)] for i in range(1, n)])

        def _partition(block, _idx):
            vals = _key_values(block, key)
            assign = np.searchsorted(boundaries, vals, side="right")
            if descending:
                assign = (n - 1) - assign
            order = np.argsort(assign, kind="stable")
            sizes = np.bincount(assign, minlength=n)
            out, start = [], 0
            for s in sizes:
                out.append(_take_rows(block, order[start:start + s]))
                start += s
            return out

        def _sort_range(block):
            return _local_sort(block, key, descending)

        # Pipelined range exchange: accumulators concatenate each round's
        # range-partitions while later rounds still partition; the final
        # per-range sort runs once per output.
        out = _push_shuffle(refs, _partition, n)
        sort_range = ray_tpu.remote(_sort_range)
        return Dataset([sort_range.remote(b) for b in out])

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._execute())
        for o in others:
            refs.extend(o._execute())
        return Dataset(refs)

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-aligned merge of two same-length datasets (reference:
        dataset.py zip — columns of both sides per row; a duplicated
        column name gets a ``_1`` suffix; non-dict rows pair into
        tuples).  One merge task per left block; only row COUNTS ride
        the driver — right-side rows move worker-to-worker through
        the store."""
        refs_a, refs_b = self._execute(), other._execute()
        rows_task = ray_tpu.remote(_block_rows)
        counts = ray_tpu.get(
            [rows_task.remote(b) for b in refs_a + refs_b],
            timeout=_get_timeout())
        counts_a, counts_b = counts[:len(refs_a)], counts[len(refs_a):]
        if sum(counts_a) != sum(counts_b):
            raise ValueError(
                f"zip requires equal row counts: {sum(counts_a)} vs "
                f"{sum(counts_b)}")
        b_starts = np.cumsum([0] + counts_b)
        zip_task = ray_tpu.remote(_zip_block)
        out, start = [], 0
        for block_a, n in zip(refs_a, counts_a):
            # Right-side blocks overlapping this left block's rows.
            picked = [(int(b_starts[j]), refs_b[j])
                      for j in range(len(refs_b))
                      if b_starts[j] < start + n
                      and b_starts[j + 1] > start]
            starts = [s for s, _ in picked]
            out.append(zip_task.remote(
                block_a, start, starts, *[r for _, r in picked]))
            start += n
        return Dataset(out)

    def random_sample(self, fraction: float, *,
                      seed: Optional[int] = None) -> "Dataset":
        """Bernoulli-sample each row with probability ``fraction``
        (reference: dataset.py random_sample), one task per block with
        a per-block derived seed so results are reproducible AND
        blocks stay independent."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1]: {fraction}")
        refs = self._execute()
        task = ray_tpu.remote(_sample_block)
        return Dataset([task.remote(b, fraction,
                                    None if seed is None else seed + i)
                        for i, b in enumerate(refs)])

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        """Split at global row indices into len(indices)+1 datasets
        (reference: dataset.py split_at_indices).  One slice task per
        (output, overlapping input block): splits keep the input's
        block granularity and format, and blocks never ride the
        driver."""
        if any(i < 0 for i in indices) or list(indices) != sorted(indices):
            raise ValueError(f"indices must be sorted and non-negative: "
                             f"{indices}")
        return self._split_at_indices(indices, self._row_counts())

    def _split_at_indices(self, indices: List[int],
                          counts: List[int]) -> List["Dataset"]:
        refs = self._block_refs
        total = sum(counts)
        starts = np.cumsum([0] + counts)
        bounds = [0] + [min(i, total) for i in indices] + [total]
        slice_task = ray_tpu.remote(_slice_block)
        out = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            blocks = []
            for j, ref in enumerate(refs):
                s, e = int(starts[j]), int(starts[j + 1])
                a, b = max(lo, s), min(hi, e)
                if b > a:
                    blocks.append(ref if (a, b) == (s, e)
                                  else slice_task.remote(ref, a - s, b - s))
            out.append(Dataset(blocks or [ray_tpu.put([])]))
        return out

    def _row_counts(self) -> List[int]:
        task = ray_tpu.remote(_block_rows)
        return ray_tpu.get([task.remote(b) for b in self._execute()],
                           timeout=_get_timeout())

    def train_test_split(self, test_size: float | int, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> tuple["Dataset", "Dataset"]:
        """(train, test) split (reference: dataset.py
        train_test_split): float test_size = fraction of rows, int =
        absolute row count; shuffle=True randomizes rows first."""
        ds = self.random_shuffle(seed=seed) if shuffle else self
        counts = ds._row_counts()  # one sweep: count + split share it
        total = sum(counts)
        if isinstance(test_size, float):
            if not 0.0 < test_size < 1.0:
                raise ValueError(
                    f"float test_size must be in (0, 1): {test_size}")
            n_test = int(total * test_size)
        else:
            if not 0 < test_size < total:
                raise ValueError(
                    f"int test_size must be in (0, {total}): {test_size}")
            n_test = test_size
        train, test = ds._split_at_indices([total - n_test], counts)
        return train, test

    def limit(self, n: int) -> "Dataset":
        blocks = self._blocks()
        out, left = [], n
        for b in blocks:
            acc = BlockAccessor(b)
            take = min(left, acc.num_rows())
            if take > 0:
                out.append(acc.slice(0, take))
                left -= take
            if left <= 0:
                break
        return Dataset([ray_tpu.put(b) for b in out])

    def split(self, n: int, *, locality_hints=None) -> List["Dataset"]:
        """Split into n datasets by whole blocks (reference: dataset.py
        split for per-worker ingest)."""
        refs = self._execute()
        if len(refs) < n:
            self = self.repartition(n)
            refs = self._block_refs
        out = [[] for _ in range(n)]
        for i, r in enumerate(refs):
            out[i % n].append(r)
        return [Dataset(rs) for rs in out]

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        from ray_tpu.data.dataset_pipeline import DatasetPipeline
        return DatasetPipeline(self, times)

    def window(self, *, blocks_per_window: int = 2) -> "DatasetPipeline":
        from ray_tpu.data.dataset_pipeline import DatasetPipeline
        return DatasetPipeline(self, 1, blocks_per_window)

    # ------------------------------------------------------------ consume
    def count(self) -> int:
        """Only row COUNTS ride the driver: counting tasks run where
        the blocks live (a driver-side sum over _blocks() would pull
        the whole dataset into driver memory just to learn its
        length)."""
        return sum(self._row_counts())

    def num_blocks(self) -> int:
        return len(self._block_refs)

    def schema(self):
        blocks = self._blocks()
        for b in blocks:
            if BlockAccessor(b).num_rows():
                return BlockAccessor(b).schema()
        return None

    def take(self, n: int = 20) -> List:
        out = []
        for b in self._blocks():
            out.extend(BlockAccessor(b).to_pylist())
            if len(out) >= n:
                break
        return out[:n]

    def take_all(self) -> List:
        out = []
        for b in self._blocks():
            out.extend(BlockAccessor(b).to_pylist())
        return out

    def show(self, n: int = 20) -> None:
        for r in self.take(n):
            print(r)

    def to_pandas(self):
        return BlockAccessor(
            BlockAccessor.combine(self._blocks())).to_pandas()

    def iter_rows(self) -> Iterable:
        for b in self._iter_local_blocks():
            yield from BlockAccessor(b).to_pylist()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: Optional[str] = "numpy",
                     drop_last: bool = False,
                     max_in_flight: int = 4) -> Iterable:
        """Stream batches.  Pending stages execute STREAMING (bounded
        window of in-flight blocks, no full materialization) and are
        NOT cached: re-iterating re-executes the chain.  Call
        .materialize() first (or consume via .repeat(n)) to pay the
        transform cost once across repeated passes."""
        carry = None
        for b in self._iter_local_blocks(max_in_flight=max_in_flight):
            if carry is not None:
                b = BlockAccessor.combine([carry, b])
                carry = None
            acc = BlockAccessor(b)
            n = acc.num_rows()
            i = 0
            while n - i >= batch_size:
                yield BlockAccessor(
                    acc.slice(i, i + batch_size)).to_batch_format(
                        batch_format)
                i += batch_size
            if i < n:
                carry = acc.slice(i, n)
        if carry is not None and not drop_last:
            yield BlockAccessor(carry).to_batch_format(batch_format)

    def iter_torch_batches(self, *, batch_size: int = 256, **kw):
        import torch
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy"):
            yield {k: torch.as_tensor(v) for k, v in batch.items()} \
                if isinstance(batch, dict) else torch.as_tensor(batch)

    def iter_jax_batches(self, *, batch_size: int = 256, sharding=None,
                         **kw):
        """TPU-native last-mile ingest: numpy batches placed on device
        (optionally with a NamedSharding for direct mesh feeding)."""
        import jax
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy"):
            if sharding is not None:
                place = lambda v: jax.device_put(v, sharding)  # noqa: E731
            else:
                place = jax.device_put
            yield ({k: place(v) for k, v in batch.items()}
                   if isinstance(batch, dict) else place(batch))

    # ------------------------------------------------------------ aggregate
    def _column(self, col: Optional[str]):
        vals = []
        for b in self._blocks():
            acc = BlockAccessor(b)
            arr = acc.to_numpy(col) if col else np.asarray(acc.to_pylist())
            vals.append(np.asarray(arr))
        return np.concatenate(vals) if vals else np.array([])

    def _aggregate_values(self, aggs) -> List:
        """Distributed accumulate: one task per block folds ALL aggs at
        once where the block lives; only accumulators ride back to the
        driver for the merge + finalize (reference: Dataset.aggregate ->
        _GroupbyOp with an empty key)."""
        refs = self._execute()
        task = ray_tpu.remote(_accumulate_aggs)
        per_block = ray_tpu.get([task.remote(b, aggs) for b in refs],
                                timeout=_get_timeout())
        out = []
        for j, agg in enumerate(aggs):
            acc = agg.init(None)
            for row in per_block:
                acc = agg.merge(acc, row[j])
            out.append(agg.finalize(acc))
        return out

    def aggregate(self, *aggs):
        """Apply one or more AggregateFns over the whole dataset
        (reference: dataset.py:1341).  Returns {name: value}."""
        if not aggs:
            raise ValueError("aggregate() needs at least one AggregateFn")
        vals = self._aggregate_values(aggs)
        return {agg.name: v for agg, v in zip(aggs, vals)}

    def sum(self, on: Optional[str] = None):
        from ray_tpu.data.aggregate import Sum
        return self._aggregate_values([Sum(on)])[0]

    def min(self, on: Optional[str] = None):
        from ray_tpu.data.aggregate import Min
        return self._aggregate_values([Min(on)])[0]

    def max(self, on: Optional[str] = None):
        from ray_tpu.data.aggregate import Max
        return self._aggregate_values([Max(on)])[0]

    def mean(self, on: Optional[str] = None):
        from ray_tpu.data.aggregate import Mean
        return self._aggregate_values([Mean(on)])[0]

    def std(self, on: Optional[str] = None, ddof: int = 1):
        from ray_tpu.data.aggregate import Std
        return self._aggregate_values([Std(on, ddof=ddof)])[0]

    # ----------------------------------------------------- blocks / export
    def get_internal_block_refs(self) -> List:
        """Materialize pending stages and return the block ObjectRefs
        (reference: Dataset.get_internal_block_refs)."""
        return list(self._execute())

    def size_bytes(self) -> int:
        """Total materialized byte size, computed where the blocks
        live (reference: Dataset.size_bytes over BlockMetadata)."""
        def _size(block):
            return BlockAccessor(block).size_bytes()
        task = ray_tpu.remote(_size)
        return sum(ray_tpu.get([task.remote(b) for b in self._execute()],
                               timeout=_get_timeout()))

    def input_files(self) -> List[str]:
        """Source files for file-reader datasets (reference:
        Dataset.input_files)."""
        return list(self._input_files)

    def randomize_block_order(self, *, seed: Optional[int] = None
                              ) -> "Dataset":
        """Shuffle BLOCK order without touching rows (reference:
        dataset.py:773).  Pure metadata: per-block stages commute with
        block order, so pending stages are carried over unchanged."""
        refs = list(self._block_refs)
        random.Random(seed).shuffle(refs)
        return Dataset(refs, self._stages, stats=self._stats,
                       input_files=self._input_files)

    def split_proportionately(self, proportions: List[float]
                              ) -> List["Dataset"]:
        """Split by fractions; the final split takes the remainder
        (reference: dataset.py:1110)."""
        if not proportions or any(p <= 0 for p in proportions):
            raise ValueError("proportions must be positive")
        if builtins.sum(proportions) >= 1.0:
            raise ValueError("proportions must sum to < 1")
        total = self.count()
        indices, acc = [], 0.0
        for p in proportions:
            acc += p
            indices.append(int(total * acc))
        return self.split_at_indices(indices)

    def to_numpy_refs(self, *, column: Optional[str] = None) -> List:
        """One ObjectRef per block holding its numpy conversion
        (reference: Dataset.to_numpy_refs)."""
        def _np(block):
            return BlockAccessor(block).to_numpy(column)
        task = ray_tpu.remote(_np)
        return [task.remote(b) for b in self._execute()]

    def to_pandas_refs(self) -> List:
        def _pd(block):
            return BlockAccessor(block).to_pandas()
        task = ray_tpu.remote(_pd)
        return [task.remote(b) for b in self._execute()]

    def to_arrow_refs(self) -> List:
        def _arrow(block):
            return BlockAccessor(block).to_arrow()
        task = ray_tpu.remote(_arrow)
        return [task.remote(b) for b in self._execute()]

    def to_torch(self, *, label_column: Optional[str] = None,
                 feature_columns: Optional[List[str]] = None,
                 batch_size: int = 256):
        """A torch IterableDataset of (features, label) tensor batches
        (reference: Dataset.to_torch).  Streams through iter_batches —
        no full materialization on the consumer."""
        import torch

        ds = self

        class _TorchIterable(torch.utils.data.IterableDataset):
            def __iter__(self):
                for batch in ds.iter_batches(batch_size=batch_size,
                                             batch_format="numpy"):
                    if not isinstance(batch, dict):
                        yield torch.as_tensor(batch)
                        continue
                    label = (torch.as_tensor(batch[label_column])
                             if label_column else None)
                    cols = feature_columns or [
                        c for c in batch if c != label_column]
                    feats = torch.stack(
                        [torch.as_tensor(np.asarray(batch[c],
                                                    dtype=np.float32))
                         for c in cols], dim=1)
                    yield (feats, label) if label is not None else feats

        return _TorchIterable()

    def iter_tf_batches(self, *, batch_size: int = 256):
        """Tensorflow batches (gated: tf is not in this image; the
        conversion itself is generic numpy->tf.constant)."""
        try:
            import tensorflow as tf  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "tensorflow is not installed in this environment; "
                "iter_tf_batches requires it") from e
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy"):
            yield ({k: tf.constant(v) for k, v in batch.items()}
                   if isinstance(batch, dict) else tf.constant(batch))

    def to_tf(self, *, label_column: Optional[str] = None,
              feature_columns: Optional[List[str]] = None,
              batch_size: int = 256):
        """A tf.data.Dataset over this dataset (gated on tf presence,
        reference: Dataset.to_tf)."""
        try:
            import tensorflow as tf
        except ImportError as e:
            raise ImportError(
                "tensorflow is not installed in this environment; "
                "to_tf requires it") from e
        first = next(self.iter_batches(batch_size=2,
                                       batch_format="numpy"), None)
        if not isinstance(first, dict):
            raise ValueError("to_tf requires a tabular dataset")
        cols = feature_columns or [c for c in first
                                   if c != label_column]

        def _gen():
            for batch in self.iter_batches(batch_size=batch_size,
                                           batch_format="numpy"):
                feats = np.stack([np.asarray(batch[c], dtype=np.float32)
                                  for c in cols], axis=1)
                if label_column:
                    yield feats, np.asarray(batch[label_column])
                else:
                    yield feats

        spec = tf.TensorSpec(shape=(None, len(cols)), dtype=tf.float32)
        if label_column:
            sig = (spec, tf.TensorSpec(shape=(None,), dtype=tf.as_dtype(
                np.asarray(first[label_column]).dtype)))
        else:
            sig = spec
        return tf.data.Dataset.from_generator(_gen, output_signature=sig)

    def write_datasource(self, datasource, **write_args) -> None:
        """Write via a Datasource's do_write seam (reference:
        Dataset.write_datasource)."""
        datasource.do_write(self._blocks(), **write_args)

    def to_random_access_dataset(self, key: str, num_workers: int = 2):
        """Distributed point-lookup index over this dataset (reference:
        dataset.py:3044 -> RandomAccessDataset)."""
        from ray_tpu.data.random_access_dataset import RandomAccessDataset
        return RandomAccessDataset(self, key, num_workers=num_workers)

    # ------------------------------------------------------- lazy/eager
    def lazy(self) -> "Dataset":
        """Datasets here are lazy by construction (stages accumulate
        until consumed); provided for reference API compatibility."""
        return self

    def experimental_lazy(self) -> "Dataset":
        return self

    def fully_executed(self) -> "Dataset":
        return self.materialize()

    def is_fully_executed(self) -> bool:
        return not self._stages

    def copy(self) -> "Dataset":
        return Dataset(self._block_refs, self._stages, stats=self._stats,
                       input_files=self._input_files)

    # ------------------------------------------------------------- output
    def write_parquet(self, path: str) -> None:
        import os
        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self._blocks()):
            BlockAccessor(b).to_arrow()
            import pyarrow.parquet as pq
            pq.write_table(BlockAccessor(b).to_arrow(),
                           f"{path}/part-{i:05d}.parquet")

    def write_csv(self, path: str) -> None:
        import os
        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self._blocks()):
            BlockAccessor(b).to_pandas().to_csv(
                f"{path}/part-{i:05d}.csv", index=False)

    def write_json(self, path: str) -> None:
        import os
        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self._blocks()):
            BlockAccessor(b).to_pandas().to_json(
                f"{path}/part-{i:05d}.json", orient="records", lines=True)

    def write_numpy(self, path: str, column: Optional[str] = None) -> None:
        import os
        os.makedirs(path, exist_ok=True)
        for i, b in enumerate(self._blocks()):
            np.save(f"{path}/part-{i:05d}.npy",
                    BlockAccessor(b).to_numpy(column))

    def __repr__(self):
        return (f"Dataset(num_blocks={len(self._block_refs)}, "
                f"pending_stages={len(self._stages)})")


def _block_rows(block) -> int:
    return BlockAccessor(block).num_rows()


def _accumulate_aggs(block, aggs):
    """Worker-side: fold every AggregateFn over one block; returns the
    list of accumulators (small — never rows)."""
    return [agg.accumulate_block(agg.init(None), block) for agg in aggs]


def _gather_rows(start: int, count: int, b_starts: List[int], *blocks):
    """Assemble global rows [start, start+count) from ``blocks`` whose
    global start offsets are ``b_starts`` (zip/split_at_indices
    worker-side helper)."""
    rows: List = []
    for bs, block in zip(b_starts, blocks):
        acc = BlockAccessor(block)
        lo, hi = max(start, bs), min(start + count, bs + acc.num_rows())
        if hi > lo:
            rows.extend(
                BlockAccessor(acc.slice(lo - bs, hi - bs)).to_pylist())
    return rows


def _zip_block(block_a, start: int, b_starts: List[int], *blocks_b):
    acc_a = BlockAccessor(block_a)
    rows_a = acc_a.to_pylist()
    rows_b = _gather_rows(start, acc_a.num_rows(), b_starts, *blocks_b)
    out: List = []
    for ra, rb in zip(rows_a, rows_b):
        if isinstance(ra, dict) and isinstance(rb, dict):
            merged = dict(ra)
            for k, v in rb.items():
                merged[k if k not in merged else f"{k}_1"] = v
            out.append(merged)
        else:
            out.append((ra, rb))
    return out


def _slice_block(block, start: int, stop: int):
    """Row-range slice preserving the block's format."""
    return BlockAccessor(block).slice(start, stop)


def _sample_block(block, fraction: float, seed: Optional[int]):
    acc = BlockAccessor(block)
    rng = np.random.default_rng(seed)
    keep = rng.random(acc.num_rows()) < fraction
    return [r for r, k in zip(acc.to_pylist(), keep) if k]


def _key_values(block, key: Optional[str]) -> np.ndarray:
    """The sort-key array of a block (key=None: the row values)."""
    acc = BlockAccessor(block)
    if key is not None:
        return np.asarray(acc.to_numpy(key))
    b = acc._b
    if isinstance(b, list):
        return np.asarray(b)
    return np.asarray(acc.to_numpy("value"))


def _local_sort(block, key: Optional[str], descending: bool):
    acc = BlockAccessor(block)
    if key is None and isinstance(acc._b, list):
        return sorted(acc._b, reverse=descending)
    if key is None:
        vals = _key_values(block, None)
        order = np.argsort(vals, kind="stable")
        if descending:
            order = order[::-1]
        return _take_rows(block, order)
    df = acc.to_pandas().sort_values(key, ascending=not descending,
                                     kind="stable")
    return df.reset_index(drop=True)


def _take_rows(block, idxs):
    acc = BlockAccessor(block)
    b = acc._b
    if isinstance(b, list):
        return [b[int(i)] for i in idxs]
    if isinstance(b, np.ndarray):
        return b[np.asarray(idxs, dtype=np.int64)]
    if isinstance(b, dict):
        return {k: np.asarray(v)[idxs] for k, v in b.items()}
    try:
        import pyarrow as pa
        if isinstance(b, pa.Table):
            return b.take(list(map(int, idxs)))
    except ImportError:
        pass
    return b.iloc[idxs]


def _block_rng(seed: int, *idx: int):
    """Per-block RNG derived from (seed, indices) — NEVER from round or
    window structure, so a seeded shuffle's row assignment is identical
    across parallelism settings and executors."""
    return np.random.default_rng([seed & ((1 << 63) - 1), *idx])


def _shuffle_partition_rows(block, idx: int, seed: int, n_out: int):
    """Assign each row of block ``idx`` to one of ``n_out`` outputs:
    one O(rows) random permutation split into even contiguous chunks
    (every output gets rows/n_out ± 1 of each block — balanced by
    construction, and ~5x cheaper than the old randint+stable-argsort
    assignment, which dominated shuffle wall time).  The final
    within-output permutation re-mixes across blocks."""
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    rng = _block_rng(seed, 1, idx)
    perm = rng.permutation(rows)
    bounds = np.linspace(0, rows, n_out + 1).astype(np.int64)
    return [_take_rows(block, perm[bounds[j]:bounds[j + 1]])
            for j in range(n_out)]


def _shuffle_finalize_rows(block, seed: int, out_idx: int):
    """Final within-output permutation, derived from (seed, out_idx)."""
    acc = BlockAccessor(block)
    rng = _block_rng(seed, 2, out_idx)
    return _take_rows(block, rng.permutation(acc.num_rows()))


def _random_shuffle_op(seed: int):
    """The streaming executor's random_shuffle as an all-to-all op."""
    from ray_tpu.data._internal.operators import AllToAllOp

    def _bind(refs):
        n_out = len(refs) or 1

        def _partition(block, idx):
            return _shuffle_partition_rows(block, idx, seed, n_out)

        def _combine(out_idx, *parts):
            block = BlockAccessor.combine(list(parts))
            return _shuffle_finalize_rows(block, seed, out_idx)

        return n_out, _partition, _combine

    return AllToAllOp("random_shuffle", _bind)


def _repartition_op(num_blocks: int):
    """Row-range repartition as an all-to-all op: the bind step counts
    rows where the blocks live; partition tasks slice their block's
    global row range, combine tasks concatenate."""
    from ray_tpu.data._internal.operators import AllToAllOp

    def _bind(refs):
        rows_task = ray_tpu.remote(_block_rows)
        counts = ray_tpu.get([rows_task.remote(b) for b in refs],
                             timeout=_get_timeout())
        total = sum(counts)
        per = (total + num_blocks - 1) // num_blocks
        starts = np.cumsum([0] + counts)

        def _partition(block, idx):
            first_row = int(starts[idx])
            acc = BlockAccessor(block)
            rows = acc.num_rows()
            out = []
            for j in range(num_blocks):
                lo = max(0, j * per - first_row)
                hi = min(rows, (j + 1) * per - first_row)
                out.append(acc.slice(lo, max(lo, hi)))
            return out

        def _combine(out_idx, *parts):
            return BlockAccessor.combine(list(parts))

        return num_blocks, _partition, _combine

    return AllToAllOp("repartition", _bind)


def from_items_single(rows: List, num_blocks: int) -> "Dataset":
    num_blocks = max(1, num_blocks)
    per = (len(rows) + num_blocks - 1) // num_blocks
    return Dataset([ray_tpu.put(rows[i * per:(i + 1) * per])
                    for i in range(num_blocks)])


class GroupedData:
    """Distributed hash-partitioned groupby (reference: data
    grouped_data.py over the all-to-all shuffle): every block hash-splits
    on the key, partition j of every block merges on a worker, and each
    merged partition aggregates locally — a key's rows all land in the
    same partition, so per-partition aggregation is exact and no block
    rides through the driver."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _partitions(self) -> List[List]:
        refs = self._ds._execute()
        n = len(refs) or 1
        key = self._key

        def _hash_part(block):
            import pandas as pd
            df = BlockAccessor(block).to_pandas()
            if n == 1:
                return df
            h = pd.util.hash_pandas_object(
                df[key], index=False).to_numpy() % n
            return [df[h == j].reset_index(drop=True) for j in range(n)]

        part_task = ray_tpu.remote(_hash_part).options(num_returns=n)
        parts = [part_task.remote(b) for b in refs]
        if n == 1:
            parts = [[p] for p in parts]
        return [[parts[i][j] for i in range(len(parts))]
                for j in range(n)]

    def _agg(self, agg_fn_name: str, on: Optional[str] = None):
        key = self._key

        def _combine_agg(*dfs):
            import pandas as pd
            df = pd.concat(dfs, ignore_index=True)
            if agg_fn_name == "count":
                return df.groupby(key).size().reset_index(name="count()")
            g = df.groupby(key)
            target = g[on] if on else g
            return getattr(target, agg_fn_name)().reset_index()

        agg_task = ray_tpu.remote(_combine_agg)
        return Dataset([agg_task.remote(*group)
                        for group in self._partitions()])

    def count(self):
        return self._agg("count")

    def sum(self, on=None):
        return self._agg("sum", on)

    def min(self, on=None):
        return self._agg("min", on)

    def max(self, on=None):
        return self._agg("max", on)

    def mean(self, on=None):
        return self._agg("mean", on)

    def std(self, on=None):
        return self._agg("std", on)

    def aggregate(self, *aggs) -> Dataset:
        """Per-group AggregateFns (reference: GroupedDataset.aggregate).
        Each hash partition folds every group's rows through each agg's
        accumulate/finalize where the partition lives; one output row
        per group keyed by the group value plus one column per agg."""
        if not aggs:
            raise ValueError("aggregate() needs at least one AggregateFn")
        key = self._key

        def _agg_part(*dfs):
            import pandas as pd
            df = pd.concat(dfs, ignore_index=True)
            rows = []
            for kval, sub in df.groupby(key):
                row = {key: kval}
                for agg in aggs:
                    acc = agg.accumulate_block(agg.init(kval), sub)
                    row[agg.name] = agg.finalize(acc)
                rows.append(row)
            return pd.DataFrame(rows)

        t = ray_tpu.remote(_agg_part)
        return Dataset([t.remote(*group) for group in self._partitions()])

    def map_groups(self, fn: Callable) -> Dataset:
        key = self._key

        def _apply(*dfs):
            import pandas as pd
            df = pd.concat(dfs, ignore_index=True)
            outs = [fn(sub) for _, sub in df.groupby(key)]
            if not outs:
                return df
            first = outs[0]
            if isinstance(first, pd.DataFrame):
                return pd.concat(outs, ignore_index=True)
            return outs

        t = ray_tpu.remote(_apply)
        return Dataset([t.remote(*group) for group in self._partitions()])
