"""Distributed datasets (the reference's Ray Data, SURVEY.md §2.3).

Blocks (Arrow/pandas/numpy/list) live in the object store; transforms are
lazy stages fused into one task per block (or an actor pool for stateful
UDFs); shuffle/sort/groupby run two-round task graphs; `iter_jax_batches`
is the TPU last-mile: numpy batches device_put with a mesh sharding.
"""

from ray_tpu.data.block import BlockAccessor  # noqa: F401
from ray_tpu.data.dataset import (  # noqa: F401
    ActorPoolStrategy, Dataset, GroupedData, TaskPoolStrategy,
)
from ray_tpu.data.dataset_pipeline import DatasetPipeline  # noqa: F401
from ray_tpu.data.read_api import (  # noqa: F401
    from_arrow, from_huggingface, from_items, from_numpy, from_pandas,
    range, range_tensor,
    read_binary_files, read_csv, read_json, read_numpy, read_parquet,
    read_text,
)
from ray_tpu.data.datasource import (  # noqa: F401
    Datasource, RangeDatasource, ReadTask, read_datasource,
)
from ray_tpu.data.aggregate import (  # noqa: F401
    AbsMax, AggregateFn, Count, Max, Mean, Min, Std, Sum,
)
from ray_tpu.data.random_access_dataset import (  # noqa: F401
    RandomAccessDataset,
)

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("data")
del _rlu
