"""Internal execution machinery for ray_tpu.data: the operator-graph
streaming executor (operators.py, streaming_executor.py) and the
transfer-plane all-to-all shuffle (shuffle.py)."""
