"""Operator-graph streaming executor for ray_tpu.data.

Reference: python/ray/data/_internal/execution/streaming_executor.py —
the pull-based executor that replaced bulk materialization as Ray
Data's default.  A Dataset's pending stage list compiles to a chain of
physical operators (operators.build_plan): chained per-block transforms
fuse into one MapOperator, all-to-all stages become ShuffleOperators
riding the transfer plane (shuffle.py).  Iteration composes the
operators' ``iter_outputs`` generators, so the whole chain is driven by
consumer pulls: while the consumer holds a batch (a train step),
already-submitted tasks keep completing remotely, and no operator
admits more input than its output budget allows.

Peak driver memory is O(sum of operator budgets + one block being
yielded); blocks between operators travel as handles, and the only
bytes fetched to the consumer are the final stage's outputs.
"""

from __future__ import annotations

import time
from typing import Iterable, Iterator, List, Optional

import ray_tpu
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu.data._internal.operators import (
    BlockHandle, build_plan, handles_for,
)


class StreamingExecutor:
    """Drive a stage list over materialized source blocks.

    ``parallelism`` bounds each operator's in-flight task window
    (default: ``cfg.data_shuffle_parallelism``, auto when <= 0);
    ``budget_bytes`` is the per-operator output budget
    (``cfg.data_op_budget_bytes``); ``locality=False`` disables
    input-location placement hints (bench baseline).
    """

    def __init__(self, block_refs: List, stages, *,
                 parallelism: Optional[int] = None,
                 budget_bytes: Optional[int] = None,
                 locality: bool = True,
                 lease=None):
        self._refs = list(block_refs)
        # ``lease``: an arbiter.DataLease bounding concurrent task
        # admission (revocable soak capacity).  None falls back to the
        # process-ambient lease, if one is installed.
        self._plan = build_plan(stages, budget_bytes=budget_bytes,
                                parallelism=parallelism,
                                locality=locality,
                                n_blocks_hint=len(self._refs),
                                lease=lease)

    def iter_handles(self) -> Iterator[BlockHandle]:
        """Compose the operator chain; yields final-stage handles."""
        stream: Iterable[BlockHandle] = handles_for(self._refs)
        self._streams = []
        for op in self._plan:
            stream = op.iter_outputs(stream)
            self._streams.append(stream)
        return iter(stream)

    def close(self):
        """Unwind the generator chain (outermost first) so every
        operator's ``finally`` cancels its in-flight window."""
        for stream in reversed(getattr(self, "_streams", [])):
            close = getattr(stream, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    def iter_blocks(self) -> Iterator:
        """Yield final blocks (fetched to the consumer) in order."""
        stream = self.iter_handles()
        t0 = time.time()
        n = 0
        try:
            for h in stream:
                yield ray_tpu.get(h.ref, timeout=cfg.data_get_timeout_s)
                n += 1
        finally:
            # Early abandon (break/islice) included: cancel everything
            # still in flight.
            self.close()
            # Execution-envelope span (consumer wall-clock included —
            # backpressure IS the story); operator tasks and their
            # transfer pulls record in worker/raylet rings under the
            # same trace.
            _tracing.record("data", "data.execute", t0,
                            time.time() - t0,
                            trace=_tracing.child_span(),
                            args={"operators": len(self._plan),
                                  "blocks_out": n})
