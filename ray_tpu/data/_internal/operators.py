"""Physical operators for the streaming Data executor.

Reference: python/ray/data/_internal/execution/operators/ — a logical
stage list compiles to a chain of physical operators; chained
map/filter/flat_map stages FUSE into one task per block, while
all-to-all stages (shuffle/repartition) break fusion and become an
exchange.  Every operator here is PULL-based: downstream `next()` is
what admits more upstream work, so a slow consumer throttles the whole
chain instead of letting completed blocks pile up on the driver.

Memory discipline: each operator keeps at most ``parallelism`` tasks in
flight AND stops admitting new input while its submitted-but-unconsumed
output bytes exceed ``cfg.data_op_budget_bytes`` — peak memory is
O(sum of operator budgets), not O(dataset).  Block BYTES never ride the
driver between operators: operators exchange :class:`BlockHandle`\\ s
(ref + size + location), and sizes/locations come from the owner's
bookkeeping (``CoreWorker.object_meta``), not from fetching.

Locality: a map task whose input block has a known location is
submitted with a SOFT ``NodeAffinitySchedulingStrategy`` so it runs
where its bytes already live; a dead/unknown target falls through to
the ordinary scheduling policy chain in the raylet.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Iterable, Iterator, List, Optional

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu.util.metrics import Counter, Gauge

# ---------------------------------------------------------------- metrics
# Exported via the per-process telemetry loop like every other registry
# metric (visible in the dashboard's prometheus scrape).
BYTES_SHUFFLED = Counter(
    "data_streaming_bytes_shuffled_total",
    "Partition bytes moved through streaming all-to-all exchanges")
BP_STALLS = Counter(
    "data_streaming_backpressure_stalls_total",
    "Times an operator paused admission because its output budget "
    "was full")
OP_QUEUED = Gauge(
    "data_streaming_op_queued_bytes",
    "Submitted-but-unconsumed output bytes per streaming operator",
    tag_keys=("op",))
LOCALITY_HITS = Counter(
    "data_streaming_locality_hits_total",
    "Streaming tasks submitted with a locality (input-block location) "
    "placement hint")


class BlockHandle:
    """A block's driver-side identity: its ref plus owner-recorded size
    and location.  The bytes stay in the store."""

    __slots__ = ("ref", "size", "location")

    def __init__(self, ref, size: Optional[int] = None, location=None):
        self.ref = ref
        self.size = size
        self.location = location


class AllToAllOp:
    """Logical all-to-all stage marker carried in ``Dataset._stages``.
    Breaks map fusion.  ``bind(refs)`` runs on the driver at execution
    time and returns ``(n_out, partition_fn, combine_fn)`` where
    ``partition_fn(block, block_index) -> [n_out blocks]`` and
    ``combine_fn(out_index, *parts) -> block``."""

    def __init__(self, name: str, bind: Callable):
        self.__name__ = name
        self.bind = bind


def _get_timeout() -> float:
    return cfg.data_get_timeout_s


def auto_parallelism(n_blocks: int) -> int:
    p = cfg.data_shuffle_parallelism
    if p and p > 0:
        return p
    return min(16, max(4, n_blocks))


def split_segments(stages) -> List:
    """Split a stage list into fusable runs: ``("map", [stage, ...])``
    segments (chained per-block transforms -> ONE task per block) and
    ``("all_to_all", op)`` breakers."""
    out: List = []
    run: List = []
    for s in stages:
        if isinstance(s[0], AllToAllOp):
            if run:
                out.append(("map", run))
                run = []
            out.append(("all_to_all", s[0]))
        else:
            run.append(s)
    if run:
        out.append(("map", run))
    return out


def _owned_meta(refs):
    from ray_tpu._private import worker as worker_mod
    w = worker_mod.global_worker
    if w is None:
        return {}
    try:
        return w.object_meta(refs)
    except Exception:
        return {}


def handles_for(refs) -> List[BlockHandle]:
    """Source handles for already-materialized block refs."""
    meta = _owned_meta(refs)
    out = []
    for r in refs:
        size, loc, _err = meta.get(r.id, (None, None, False))
        out.append(BlockHandle(r, size or None, loc))
    return out


def resolve_handle(handle: BlockHandle, timeout: Optional[float] = None
                   ) -> BlockHandle:
    """Block until the handle's task finished (readiness only — no byte
    movement), then fill in actual size/location.  An errored block
    raises its task error here."""
    timeout = timeout if timeout is not None else _get_timeout()
    ready, _ = ray_tpu.wait([handle.ref], num_returns=1, timeout=timeout,
                            fetch_local=False)
    if not ready:
        from ray_tpu.exceptions import GetTimeoutError
        raise GetTimeoutError(
            f"streaming block not ready within {timeout}s")
    meta = _owned_meta([handle.ref])
    size, loc, err = meta.get(handle.ref.id, (None, None, False))
    if err:
        ray_tpu.get(handle.ref, timeout=timeout)  # raises the task error
    handle.size = size or handle.size
    handle.location = loc
    return handle


def locality_opts(location, enabled: bool = True) -> dict:
    """Task options pinning (softly) to the node holding the input
    bytes; {} when the location is unknown or locality is off."""
    if not enabled or location is None:
        return {}
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)
    LOCALITY_HITS.inc(1)
    return {"scheduling_strategy":
            NodeAffinitySchedulingStrategy(node_id=location, soft=True)}


def _apply_fused(fn, block):
    return fn(block)


class MapOperator:
    """Fused per-block transform run: keeps at most ``parallelism``
    tasks in flight, admits new input only while queued output bytes
    stay under ``budget_bytes`` (one task is always admitted so a block
    larger than the budget still progresses), and yields outputs in
    input order."""

    def __init__(self, fused_fn: Callable, name: str = "map", *,
                 budget_bytes: Optional[int] = None,
                 parallelism: Optional[int] = None,
                 locality: bool = True,
                 n_blocks_hint: Optional[int] = None,
                 lease=None):
        self.fused_fn = fused_fn
        self.name = name
        self.budget = budget_bytes or cfg.data_op_budget_bytes
        self.parallelism = parallelism
        self.locality = locality
        self.n_blocks_hint = n_blocks_hint
        # Revocable autopilot soak lease (arbiter.DataLease): admission
        # is additionally bounded by lease.allowed() each round, so a
        # broker revocation stops NEW task launches immediately while
        # the in-flight window drains within the grace period — the
        # clean-backpressure half of the revocable-lease contract.
        self.lease = lease

    def iter_outputs(self, upstream: Iterable[BlockHandle]
                     ) -> Iterator[BlockHandle]:
        from ray_tpu._private import arbiter as _arbiter
        task = ray_tpu.remote(_apply_fused)
        src = iter(upstream)
        in_flight: deque = deque()  # [handle(out_ref), est_bytes]
        queued_gauge = OP_QUEUED.series(tags={"op": self.name})
        est_avg = None
        # The upstream is an iterator (block count unknown here), so
        # auto sizing uses the executor's source-count hint.
        window = self.parallelism or auto_parallelism(
            self.n_blocks_hint or 8)
        exhausted = False
        lease = self.lease or _arbiter.ambient_data_lease()

        def _queued():
            return sum(e for _, e in in_flight)

        try:
            while True:
                budget_blocked = False
                cap = window
                if lease is not None:
                    cap = min(window, max(lease.allowed(), 0))
                while not exhausted and len(in_flight) < cap:
                    if in_flight and _queued() >= self.budget:
                        budget_blocked = True
                        break
                    try:
                        h = next(src)
                    except StopIteration:
                        exhausted = True
                        break
                    opts = locality_opts(h.location, self.locality)
                    out = task.options(**opts).remote(self.fused_fn,
                                                      h.ref) \
                        if opts else task.remote(self.fused_fn, h.ref)
                    est = h.size or est_avg or (1 << 20)
                    in_flight.append([BlockHandle(out), est])
                    if lease is not None:
                        lease.note_launched()
                if not in_flight:
                    if not exhausted and lease is not None and cap <= 0:
                        # Lease revoked to zero with nothing in flight:
                        # hold admission (clean backpressure) and poll
                        # for a re-grant instead of finishing early.
                        BP_STALLS.inc(1)
                        queued_gauge.set(0.0)
                        time.sleep(0.05)
                        continue
                    queued_gauge.set(0.0)
                    return
                if budget_blocked:
                    BP_STALLS.inc(1)
                head, est = in_flight[0]
                resolve_handle(head)
                in_flight.popleft()
                if lease is not None:
                    lease.note_finished()
                if head.size:
                    est_avg = (head.size if est_avg is None
                               else 0.5 * (est_avg + head.size))
                queued_gauge.set(float(_queued()))
                yield head
        finally:
            # Consumer abandoned the stream: cancel the unread window.
            for h, _ in in_flight:
                try:
                    ray_tpu.cancel(h.ref)
                except Exception:
                    pass
            queued_gauge.set(0.0)


class ShuffleOperator:
    """All-to-all exchange operator; the heavy lifting (windowed
    partition maps, transfer-plane reduce pulls, locality scoring)
    lives in shuffle.exchange."""

    def __init__(self, op: AllToAllOp, *,
                 budget_bytes: Optional[int] = None,
                 parallelism: Optional[int] = None,
                 locality: bool = True):
        self.op = op
        self.name = op.__name__
        self.budget = budget_bytes or cfg.data_op_budget_bytes
        self.parallelism = parallelism
        self.locality = locality

    def iter_outputs(self, upstream: Iterable[BlockHandle]
                     ) -> Iterator[BlockHandle]:
        from ray_tpu.data._internal.shuffle import exchange
        return exchange(upstream, self.op, parallelism=self.parallelism,
                        budget_bytes=self.budget, locality=self.locality)


def build_plan(stages, *, budget_bytes=None, parallelism=None,
               locality: bool = True, n_blocks_hint=None,
               lease=None) -> List:
    """Compile a Dataset stage list into the physical operator chain."""
    from ray_tpu.data.dataset import Dataset
    plan: List = []
    for kind, seg in split_segments(stages):
        if kind == "map":
            names = "+".join(getattr(s[0], "__name__", "stage").lstrip("_")
                             for s in seg)
            plan.append(MapOperator(Dataset._fuse(seg), names,
                                    budget_bytes=budget_bytes,
                                    parallelism=parallelism,
                                    locality=locality,
                                    n_blocks_hint=n_blocks_hint,
                                    lease=lease))
        else:
            plan.append(ShuffleOperator(seg,
                                        budget_bytes=budget_bytes,
                                        parallelism=parallelism,
                                        locality=locality))
    return plan
