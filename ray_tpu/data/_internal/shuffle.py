"""Windowed all-to-all shuffle riding the object transfer plane.

Reference: python/ray/data/_internal/push_based_shuffle.py, re-based on
this repo's transfer plane (PR 4/7): instead of folding every round's
partitions into accumulator objects (each fold re-fetches, re-combines
and re-serializes the running block, so the same bytes cross the store
``rounds`` times), every input block is partitioned ONCE where it lives
and every output is combined ONCE where most of its partition bytes
live.  The partition movement is the reduce task's argument fetch —
which is exactly ``TransferManager.pull``: windowed chunk requests,
multi-source striping via the GCS object directory, spill-aware through
the cached-fd pread path, and per-peer in-flight byte caps.  Bytes move
exactly once, and they never touch the driver.

Fault model: partition refs are owned by the driver, so a node dying
mid-shuffle surfaces as a lost partition when a reduce fetches it; the
owner's copy-holder check (PR 5 ``_object_source_alive``) distinguishes
a partitioned-but-alive source (retry) from a dead one, and lineage
reconstruction re-runs ONLY the map tasks whose partitions were
actually lost — the rest of the exchange is untouched.

Backpressure: partition maps run in a bounded window; reduces are
admitted while ``parallelism`` and the output byte budget allow, and
outputs stream to the consumer in output-index order (deterministic
regardless of the window size).  Consumed partition columns are
released eagerly so a larger-than-memory shuffle's store pressure
drains as outputs are consumed (spill absorbs the rest).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Iterable, Iterator, Optional

import ray_tpu
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu.data._internal.operators import (
    AllToAllOp, BlockHandle, BYTES_SHUFFLED, BP_STALLS, OP_QUEUED,
    auto_parallelism, handles_for, locality_opts, resolve_handle,
    _owned_meta,
)


def _combine_task(combine_fn, out_idx, *parts):
    return combine_fn(out_idx, *parts)


def exchange(upstream: Iterable[BlockHandle], op: AllToAllOp, *,
             parallelism: Optional[int] = None,
             budget_bytes: Optional[int] = None,
             locality: bool = True) -> Iterator[BlockHandle]:
    """Run one all-to-all exchange; yields output handles in output
    order.  Drains ``upstream`` first (an all-to-all is a pipeline
    breaker: every output needs a partition from every input)."""
    handles = [h for h in upstream]
    n_in = len(handles)
    if n_in == 0:
        return
    budget = budget_bytes or cfg.data_op_budget_bytes
    window = parallelism or auto_parallelism(n_in)
    n_out, partition_fn, combine_fn = op.bind([h.ref for h in handles])
    if n_out == 1:
        # num_returns=1 would store the 1-element partition LIST as the
        # object's value, nesting blocks inside blocks at the combine
        # (rows became block-lists).  Unwrap at the source.
        _multi = partition_fn

        def partition_fn(block, idx, _multi=_multi):  # noqa: F811
            return _multi(block, idx)[0]
    queued_gauge = OP_QUEUED.series(tags={"op": op.__name__})

    # ---- map phase: partition every block where it lives, windowed.
    t_map = time.time()
    part_task = ray_tpu.remote(partition_fn)
    parts: list = [None] * n_in  # block index -> [n_out refs]
    submitted = 0
    in_flight: deque = deque()  # block indices with unresolved maps
    try:
        while submitted < n_in or in_flight:
            while submitted < n_in and len(in_flight) < window:
                h = handles[submitted]
                opts = dict(locality_opts(h.location, locality))
                opts["num_returns"] = n_out
                out = part_task.options(**opts).remote(h.ref, submitted)
                parts[submitted] = out if isinstance(out, list) else [out]
                in_flight.append(submitted)
                submitted += 1
            idx = in_flight.popleft()
            # Readiness of the first return implies the task finished
            # (all returns land together); surfaces map errors eagerly.
            resolve_handle(BlockHandle(parts[idx][0]))
    except BaseException:
        # A failed/abandoned map phase must not leave the rest of the
        # window partitioning a dataset nobody will reduce.
        for idx in in_flight:
            try:
                ray_tpu.cancel(parts[idx][0])
            except Exception:
                pass
        raise

    # Partition metadata: sizes feed the shuffle-bytes accounting and
    # the locality score; locations come from the owner's bookkeeping
    # (same source the GCS object directory is fed from).
    flat = [r for col in parts for r in col]
    meta = _owned_meta(flat)
    moved = sum(m[0] for m in meta.values())
    BYTES_SHUFFLED.inc(float(moved))
    # Exchange map-phase span (driver side): the per-task execution and
    # transfer-pull spans live in worker/raylet rings; this records the
    # phase envelope + byte accounting in the request's trace.
    _tracing.record("data", "data.shuffle_map", t_map,
                    time.time() - t_map,
                    trace=_tracing.child_span(),
                    args={"op": op.__name__, "blocks": n_in,
                          "partitions": n_out, "bytes": moved})
    t_reduce = time.time()

    def _reduce_affinity(j):
        """The node holding the most bytes of output j's partitions —
        pull less, combine where the data already is."""
        score: dict = {}
        for i in range(n_in):
            size, loc, _err = meta.get(parts[i][j].id, (0, None, False))
            if loc is not None:
                score[loc] = score.get(loc, 0) + (size or 0)
        if not score:
            return None
        return max(score.items(), key=lambda kv: kv[1])[0]

    # ---- reduce phase: one combine per output, windowed + budgeted.
    reduce_task = ray_tpu.remote(_combine_task)
    pending: deque = deque()  # (out_idx, BlockHandle, est_bytes)
    est = max(1, moved // max(1, n_out))
    next_out = 0

    def _queued():
        return sum(e for _, _, e in pending)

    try:
        while next_out < n_out or pending:
            budget_blocked = False
            while next_out < n_out and len(pending) < window:
                if pending and _queued() >= budget:
                    budget_blocked = True
                    break
                j = next_out
                opts = locality_opts(_reduce_affinity(j), locality)
                cols = [parts[i][j] for i in range(n_in)]
                ref = (reduce_task.options(**opts) if opts
                       else reduce_task).remote(combine_fn, j, *cols)
                pending.append((j, BlockHandle(ref), est))
                next_out += 1
            if not pending:
                break
            if budget_blocked:
                BP_STALLS.inc(1)
            j, head, _e = pending[0]
            resolve_handle(head)
            pending.popleft()
            # This output's partition column is consumed: release the
            # refs so the store (or its spill) can reclaim them while
            # the rest of the exchange is still running.
            for i in range(n_in):
                parts[i][j] = None
            queued_gauge.set(float(_queued()))
            yield head
    finally:
        for _j, h, _e in pending:
            try:
                ray_tpu.cancel(h.ref)
            except Exception:
                pass
        queued_gauge.set(0.0)
        _tracing.record("data", "data.shuffle_reduce", t_reduce,
                        time.time() - t_reduce,
                        trace=_tracing.child_span(),
                        args={"op": op.__name__, "outputs": n_out,
                              "abandoned": len(pending)})


def exchange_bulk(refs, op: AllToAllOp, *, parallelism=None,
                  locality: bool = True) -> list:
    """Materializing form (Dataset._execute): drain the exchange and
    return the output refs in order.  No output budget — the caller
    wants everything — but maps/reduces still run windowed."""
    out = [h.ref for h in exchange(handles_for(refs), op,
                                   parallelism=parallelism,
                                   budget_bytes=1 << 62,
                                   locality=locality)]
    return out
