"""Accumulator-style aggregations (reference: python/ray/data/aggregate.py:28
AggregateFn and the Count/Sum/Min/Max/Mean/Std/AbsMax family).

Design: an AggregateFn is (init, accumulate_block, merge, finalize).
`Dataset.aggregate` runs one accumulate task per block where the block
lives, then merges the per-block accumulators on the driver — only
accumulators (scalars/small tuples) ride the control plane, never rows.
The vectorized `accumulate_block` operates on a numpy column at once
instead of the reference's per-row fallback loop.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np


class AggregateFn:
    def __init__(self, init: Callable[[Any], Any],
                 merge: Callable[[Any, Any], Any],
                 accumulate_row: Optional[Callable[[Any, Any], Any]] = None,
                 accumulate_block: Optional[Callable[[Any, Any], Any]] = None,
                 finalize: Callable[[Any], Any] = lambda a: a,
                 name: Optional[str] = None):
        if (accumulate_row is None) == (accumulate_block is None):
            raise ValueError("Exactly one of accumulate_row or "
                             "accumulate_block must be provided.")
        if accumulate_block is None:
            def accumulate_block(a, block):
                from ray_tpu.data.block import BlockAccessor
                for r in BlockAccessor(block).to_pylist():
                    a = accumulate_row(a, r)
                return a
        self.init = init
        self.merge = merge
        self.accumulate_block = accumulate_block
        self.finalize = finalize
        self.name = name or "agg()"


def _column(block, on: Optional[str]) -> np.ndarray:
    from ray_tpu.data.block import BlockAccessor
    acc = BlockAccessor(block)
    if on is None:
        return np.asarray(acc.to_pylist())
    return np.asarray(acc.to_numpy(on))


class Count(AggregateFn):
    def __init__(self):
        from ray_tpu.data.block import BlockAccessor
        super().__init__(
            init=lambda k: 0,
            accumulate_block=lambda a, b: a + BlockAccessor(b).num_rows(),
            merge=lambda a1, a2: a1 + a2,
            name="count()")


class Sum(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda k: 0,
            accumulate_block=lambda a, b: a + _column(b, on).sum(),
            merge=lambda a1, a2: a1 + a2,
            name=f"sum({on or ''})")


class Min(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda k: None,
            accumulate_block=lambda a, b: _nanless_min(a, _column(b, on)),
            merge=lambda a1, a2: _merge_opt(min, a1, a2),
            name=f"min({on or ''})")


class Max(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda k: None,
            accumulate_block=lambda a, b: _nanless_max(a, _column(b, on)),
            merge=lambda a1, a2: _merge_opt(max, a1, a2),
            name=f"max({on or ''})")


class AbsMax(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda k: None,
            accumulate_block=lambda a, b: _nanless_max(
                a, np.abs(_column(b, on))),
            merge=lambda a1, a2: _merge_opt(max, a1, a2),
            name=f"abs_max({on or ''})")


class Mean(AggregateFn):
    def __init__(self, on: Optional[str] = None):
        super().__init__(
            init=lambda k: (0.0, 0),
            accumulate_block=lambda a, b: _mean_acc(a, _column(b, on)),
            merge=lambda a1, a2: (a1[0] + a2[0], a1[1] + a2[1]),
            finalize=lambda a: a[0] / a[1] if a[1] else None,
            name=f"mean({on or ''})")


class Std(AggregateFn):
    """Sample standard deviation via the parallel (n, sum, sumsq)
    merge — numerically adequate for tests/ML feature scales and
    embarrassingly mergeable (the reference uses Welford M2 with the
    same merge topology)."""

    def __init__(self, on: Optional[str] = None, ddof: int = 1):
        def fin(a):
            n, s, ss = a
            if n <= ddof:
                return None
            var = max(0.0, (ss - s * s / n) / (n - ddof))
            return float(np.sqrt(var))
        super().__init__(
            init=lambda k: (0, 0.0, 0.0),
            accumulate_block=lambda a, b: _std_acc(a, _column(b, on)),
            merge=lambda a1, a2: (a1[0] + a2[0], a1[1] + a2[1],
                                  a1[2] + a2[2]),
            finalize=fin,
            name=f"std({on or ''})")


def _merge_opt(op, a1, a2):
    if a1 is None:
        return a2
    if a2 is None:
        return a1
    return op(a1, a2)


def _nanless_min(a, col):
    if col.size == 0:
        return a
    v = col.min()
    return v if a is None else min(a, v)


def _nanless_max(a, col):
    if col.size == 0:
        return a
    v = col.max()
    return v if a is None else max(a, v)


def _mean_acc(a, col):
    return (a[0] + float(col.sum()), a[1] + int(col.size))


def _std_acc(a, col):
    col = col.astype(np.float64, copy=False)
    return (a[0] + int(col.size), a[1] + float(col.sum()),
            a[2] + float((col * col).sum()))
