"""Datasource: the pluggable boundary for custom readers/writers.

Reference: python/ray/data/datasource/datasource.py — Datasource with
prepare_read -> ReadTasks (each a no-arg callable producing blocks) and
do_write; read_datasource runs the read tasks as cluster tasks, one block
per ReadTask.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class ReadTask:
    """A serializable unit of reading: calling it yields one block."""

    def __init__(self, read_fn: Callable[[], Any],
                 metadata: Optional[dict] = None):
        self._read_fn = read_fn
        self.metadata = metadata or {}

    def __call__(self):
        return self._read_fn()


class Datasource:
    def prepare_read(self, parallelism: int, **read_args) -> List[ReadTask]:
        raise NotImplementedError

    def do_write(self, blocks: List, **write_args) -> None:
        raise NotImplementedError


class RangeDatasource(Datasource):
    """Example in-tree datasource (reference: datasource.py
    RangeDatasource)."""

    def prepare_read(self, parallelism: int, n: int = 0,
                     **read_args) -> List[ReadTask]:
        per = max(1, (n + parallelism - 1) // parallelism)
        tasks = []
        for start in range(0, n, per):
            end = min(start + per, n)
            tasks.append(ReadTask(
                lambda s=start, e=end: list(range(s, e)),
                {"num_rows": end - start}))
        return tasks


def read_datasource(datasource: Datasource, *, parallelism: int = 8,
                    **read_args):
    """Run the datasource's read tasks as cluster tasks -> Dataset
    (reference: read_api.py read_datasource)."""
    from ray_tpu.data.dataset import Dataset
    tasks = datasource.prepare_read(parallelism, **read_args)

    @ray_tpu.remote
    def _run_read(task: ReadTask):
        return task()

    return Dataset([_run_read.remote(t) for t in tasks])
