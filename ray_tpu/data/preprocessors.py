"""Preprocessors: fit on a Dataset, transform Datasets/batches.

Reference: python/ray/data/preprocessors — Preprocessor base with
fit/transform/fit_transform, StandardScaler, MinMaxScaler, LabelEncoder,
Chain, BatchMapper.  Fit statistics aggregate per block as tasks and
combine on the driver (sufficient statistics only — never the data).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

import ray_tpu


class Preprocessor:
    _fitted = False

    def _fit(self, ds) -> None:
        raise NotImplementedError

    def _transform_pandas(self, df):
        raise NotImplementedError

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted and type(self)._fit is not Preprocessor._fit:
            raise RuntimeError(f"{type(self).__name__} not fitted")
        fn = self._transform_pandas
        return ds.map_batches(
            lambda df: fn(df), batch_format="pandas")

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform_batch(self, df):
        return self._transform_pandas(df)


def _block_stats(columns):
    def _stats(df):
        out = {}
        for c in columns:
            v = df[c].to_numpy(dtype=np.float64)
            out[c] = (len(v), v.sum(), (v ** 2).sum(), v.min() if len(v)
                      else np.inf, v.max() if len(v) else -np.inf)
        return [out]
    return _stats


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference:
    preprocessors/scaler.py StandardScaler)."""

    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        rows = ds.map_batches(_block_stats(self.columns),
                              batch_format="pandas").take_all()
        for c in self.columns:
            n = sum(r[c][0] for r in rows)
            s = sum(r[c][1] for r in rows)
            ss = sum(r[c][2] for r in rows)
            mean = s / max(n, 1)
            var = max(ss / max(n, 1) - mean ** 2, 0.0)
            self.stats_[c] = (mean, var ** 0.5 or 1.0)

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            mean, std = self.stats_[c]
            df[c] = (df[c] - mean) / (std or 1.0)
        return df


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = list(columns)
        self.stats_: Dict[str, tuple] = {}

    def _fit(self, ds):
        rows = ds.map_batches(_block_stats(self.columns),
                              batch_format="pandas").take_all()
        for c in self.columns:
            lo = min(r[c][3] for r in rows)
            hi = max(r[c][4] for r in rows)
            self.stats_[c] = (lo, hi)

    def _transform_pandas(self, df):
        df = df.copy()
        for c in self.columns:
            lo, hi = self.stats_[c]
            rng = (hi - lo) or 1.0
            df[c] = (df[c] - lo) / rng
        return df


class LabelEncoder(Preprocessor):
    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Dict = {}

    def _fit(self, ds):
        col = self.label_column
        uniques = ds.map_batches(
            lambda df: [set(df[col].unique().tolist())],
            batch_format="pandas").take_all()
        all_vals = sorted(set().union(*uniques))
        self.classes_ = {v: i for i, v in enumerate(all_vals)}

    def _transform_pandas(self, df):
        df = df.copy()
        df[self.label_column] = df[self.label_column].map(self.classes_)
        return df


class BatchMapper(Preprocessor):
    """Stateless per-batch UDF (reference: preprocessors/batch_mapper)."""

    def __init__(self, fn: Callable, batch_format: str = "pandas"):
        self._fn = fn
        self._batch_format = batch_format
        self._fitted = True

    def _fit(self, ds):
        pass

    def transform(self, ds):
        return ds.map_batches(self._fn, batch_format=self._batch_format)

    def _transform_pandas(self, df):
        return self._fn(df)


class Chain(Preprocessor):
    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def fit(self, ds):
        for p in self.preprocessors:
            ds = p.fit_transform(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def _transform_pandas(self, df):
        for p in self.preprocessors:
            df = p._transform_pandas(df)
        return df
