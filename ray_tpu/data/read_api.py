"""Dataset creation (reference: python/ray/data/read_api.py — range,
from_items/numpy/pandas/arrow, read_parquet/csv/json/numpy/binary/text)."""

from __future__ import annotations

import builtins
import glob as _glob
import os
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.dataset import Dataset

DEFAULT_PARALLELISM = 8


def _put_blocks(blocks: List) -> Dataset:
    return Dataset([ray_tpu.put(b) for b in blocks])


def range(n: int, *, parallelism: int = DEFAULT_PARALLELISM) -> Dataset:  # noqa: A001
    k = max(1, min(parallelism, n or 1))
    per = (n + k - 1) // k
    blocks = [list(builtins.range(i * per, min(n, (i + 1) * per)))
              for i in builtins.range(k)]
    return _put_blocks([b for b in blocks if b] or [[]])


def range_tensor(n: int, *, shape=(1,),
                 parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    k = max(1, min(parallelism, n or 1))
    per = (n + k - 1) // k
    blocks = []
    for i in builtins.range(k):
        lo, hi = i * per, min(n, (i + 1) * per)
        if lo >= hi:
            continue
        idx = np.arange(lo, hi).reshape((-1,) + (1,) * len(shape))
        blocks.append({"data": np.broadcast_to(
            idx, (hi - lo,) + tuple(shape)).copy()})
    return _put_blocks(blocks or [{"data": np.zeros((0,) + tuple(shape))}])


def from_items(items: List, *, parallelism: int = DEFAULT_PARALLELISM
               ) -> Dataset:
    import builtins
    k = max(1, min(parallelism, len(items) or 1))
    per = (len(items) + k - 1) // k
    blocks = [items[i * per:(i + 1) * per] for i in builtins.range(k)]
    return _put_blocks([b for b in blocks if b] or [[]])


def from_numpy(arr: np.ndarray, column: str = "data",
               parallelism: int = DEFAULT_PARALLELISM) -> Dataset:
    import builtins
    parts = np.array_split(arr, max(1, min(parallelism, len(arr) or 1)))
    return _put_blocks([{column: p} for p in parts if len(p)]
                       or [{column: arr[:0]}])


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _put_blocks(dfs)


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _put_blocks(tables)


def _expand(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in _glob.glob(os.path.join(p, "**"), recursive=True)
                if os.path.isfile(f)))
        else:
            out.extend(sorted(_glob.glob(p)) or [p])
    return out


def _read_files(paths, reader) -> Dataset:
    files = _expand(paths)
    task = ray_tpu.remote(reader)
    return Dataset([task.remote(f) for f in files], input_files=files)


def read_parquet(paths, **kw) -> Dataset:
    def _read(f):
        import pyarrow.parquet as pq
        # Registering the tensor extension in the READING process lets
        # pyarrow reconstruct ArrowTensorType columns from the file's
        # field metadata (read tasks run in worker processes that may
        # not have imported the data layer yet).
        import ray_tpu.air.util.tensor_extensions  # noqa: F401
        return pq.read_table(f)
    return _read_files(paths, _read)


def read_csv(paths, **kw) -> Dataset:
    def _read(f):
        import pandas as pd
        return pd.read_csv(f)
    return _read_files(paths, _read)


def read_json(paths, **kw) -> Dataset:
    def _read(f):
        import pandas as pd
        return pd.read_json(f, orient="records", lines=True)
    return _read_files(paths, _read)


def read_numpy(paths, **kw) -> Dataset:
    def _read(f):
        return {"data": np.load(f)}
    return _read_files(paths, _read)


def read_text(paths, **kw) -> Dataset:
    def _read(f):
        with open(f) as fh:
            return [line.rstrip("\n") for line in fh]
    return _read_files(paths, _read)


def read_binary_files(paths, **kw) -> Dataset:
    def _read(f):
        with open(f, "rb") as fh:
            return [{"path": f, "bytes": fh.read()}]
    return _read_files(paths, _read)


def from_huggingface(dataset, parallelism: int = DEFAULT_PARALLELISM
                     ) -> Dataset:
    """A HuggingFace datasets.Dataset -> blocks (reference:
    read_api.from_huggingface)."""
    import builtins
    df = dataset.to_pandas()
    n = max(1, min(parallelism, len(df) or 1))
    return from_pandas([df.iloc[i::n].reset_index(drop=True)
                        for i in builtins.range(n)])
