"""DatasetPipeline: windowed/repeated streaming over a Dataset for
compute/ingest overlap (reference: python/ray/data/dataset_pipeline.py)."""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from ray_tpu.data.dataset import Dataset


class DatasetPipeline:
    def __init__(self, ds: Dataset, times: Optional[int] = None,
                 blocks_per_window: Optional[int] = None):
        self._ds = ds
        self._times = times
        self._bpw = blocks_per_window
        self._stages = []

    def map_batches(self, fn, **kw) -> "DatasetPipeline":
        self._stages.append(("map_batches", fn, kw))
        return self

    def random_shuffle_each_window(self, **kw) -> "DatasetPipeline":
        self._stages.append(("random_shuffle", None, kw))
        return self

    def _apply(self, ds: Dataset) -> Dataset:
        for name, fn, kw in self._stages:
            ds = getattr(ds, name)(fn, **kw) if fn else \
                getattr(ds, name)(**kw)
        return ds

    def iter_epochs(self) -> Iterable[Dataset]:
        import itertools
        # Repeated consumption: materialize the BASE dataset's pending
        # stages once up front, so N epochs don't re-run the transform
        # chain N times through the streaming iterator (per-window/
        # per-epoch stages added on the pipeline still run per epoch —
        # that is their contract, e.g. random_shuffle_each_window).
        if self._times is None or self._times > 1:
            self._ds._execute()
        it = (range(self._times) if self._times is not None
              else itertools.count())
        for _ in it:
            yield self._apply(Dataset(self._ds._block_refs,
                                      self._ds._stages))

    def iter_batches(self, **kw) -> Iterable:
        for epoch_ds in self.iter_epochs():
            yield from epoch_ds.iter_batches(**kw)

    def iter_rows(self) -> Iterable:
        for epoch_ds in self.iter_epochs():
            yield from epoch_ds.iter_rows()
