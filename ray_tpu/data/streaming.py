"""Streaming execution: bounded-in-flight block processing.

Reference: python/ray/data/_internal/pipeline_executor.py (windowed
pipeline execution) and the streaming_executor that replaced bulk
execution as Ray Data's default — instead of materializing every stage
over the whole dataset before the first batch is readable, the fused
stage chain runs as a sliding window of per-block tasks: at most
`max_in_flight` blocks are being transformed or held locally at once,
and results stream to the consumer in order while later blocks are
still executing.

Peak driver memory is O(max_in_flight * block size) instead of
O(dataset size), and time-to-first-batch is one block's latency instead
of the whole stage graph's.

LEGACY (RT_DATA_STREAMING=0): superseded as the default consume path by
the operator-graph executor in data/_internal/streaming_executor.py
(fused operators with output budgets, transfer-plane all-to-all,
locality placement); kept as the bench baseline and the escape hatch.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Iterable, List

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG as cfg


class StreamingExecutor:
    def __init__(self, block_refs: List, fused_fn: Callable,
                 max_in_flight: int = 4):
        self._refs = list(block_refs)
        self._fused = fused_fn
        self._window = max(1, int(max_in_flight))

    def iter_blocks(self) -> Iterable:
        """Yield transformed blocks IN ORDER with a bounded number of
        outstanding transform tasks."""
        from ray_tpu.data.dataset import _apply_stage_task
        task = ray_tpu.remote(_apply_stage_task)
        src = iter(self._refs)
        in_flight: deque = deque()

        def _submit_next() -> bool:
            try:
                ref = next(src)
            except StopIteration:
                return False
            in_flight.append(task.remote(self._fused, ref, (), {}))
            return True

        try:
            for _ in range(self._window):
                if not _submit_next():
                    break
            while in_flight:
                head = in_flight.popleft()
                # cfg.data_get_timeout_s (RT_DATA_GET_TIMEOUT_S): the
                # data layer's unified get deadline (was a hardcoded
                # 600 s module constant).
                block = ray_tpu.get(head, timeout=cfg.data_get_timeout_s)
                # Refill the window BEFORE yielding: the consumer may
                # hold the batch for a long time (training step) and
                # the next blocks should be transforming meanwhile.
                _submit_next()
                yield block
        finally:
            # Consumer abandoned the generator early (break/islice):
            # cancel the outstanding window so unread transforms don't
            # burn the cluster.
            for ref in in_flight:
                try:
                    ray_tpu.cancel(ref)
                except Exception:
                    pass
