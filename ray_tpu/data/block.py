"""Blocks: the unit of distributed data.

Reference: python/ray/data/block.py (Block = Arrow table / pandas frame /
simple list, wrapped by a BlockAccessor).  Blocks live in the object store
and flow between transform tasks as ObjectRefs.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import numpy as np


class BlockAccessor:
    """Uniform view over the supported block formats: list-of-rows,
    dict-of-numpy ("numpy batch"), pandas.DataFrame, pyarrow.Table."""

    def __init__(self, block: Any):
        self._b = block

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        return BlockAccessor(block)

    # -- introspection -------------------------------------------------
    def num_rows(self) -> int:
        b = self._b
        if isinstance(b, list):
            return len(b)
        if isinstance(b, dict):
            return len(next(iter(b.values()))) if b else 0
        return len(b)  # pandas / arrow both define __len__

    def size_bytes(self) -> int:
        b = self._b
        if isinstance(b, list):
            import sys
            return sum(sys.getsizeof(r) for r in b)
        if isinstance(b, dict):
            return sum(np.asarray(v).nbytes for v in b.values())
        try:
            import pyarrow as pa
            if isinstance(b, pa.Table):
                return b.nbytes
        except ImportError:
            pass
        return int(b.memory_usage(deep=True).sum())  # pandas

    def schema(self):
        b = self._b
        if isinstance(b, list):
            return type(b[0]).__name__ if b else None
        if isinstance(b, dict):
            return {k: np.asarray(v).dtype for k, v in b.items()}
        try:
            import pyarrow as pa
            if isinstance(b, pa.Table):
                return b.schema
        except ImportError:
            pass
        return b.dtypes

    # -- conversion ----------------------------------------------------
    def to_pylist(self) -> List:
        b = self._b
        if isinstance(b, list):
            return list(b)
        if isinstance(b, dict):
            keys = list(b)
            n = self.num_rows()
            return [{k: np.asarray(b[k])[i] for k in keys}
                    for i in range(n)]
        try:
            import pyarrow as pa
            if isinstance(b, pa.Table):
                return b.to_pylist()
        except ImportError:
            pass
        return b.to_dict("records")

    def to_numpy(self, column: Optional[str] = None):
        b = self._b
        if isinstance(b, dict):
            return np.asarray(b[column]) if column else \
                {k: np.asarray(v) for k, v in b.items()}
        try:
            import pyarrow as pa
            if isinstance(b, pa.Table):
                from ray_tpu.air.util.tensor_extensions import (
                    is_tensor_type, tensor_column_to_numpy)

                def _col(name):
                    col = b.column(name)
                    if is_tensor_type(col.type):
                        return tensor_column_to_numpy(col)
                    return col.to_numpy(zero_copy_only=False)

                if column:
                    return _col(column)
                return {name: _col(name) for name in b.column_names}
        except ImportError:
            pass
        if isinstance(b, list):
            if b and isinstance(b[0], dict):
                keys = b[0].keys()
                out = {k: np.asarray([r[k] for r in b]) for k in keys}
                return out[column] if column else out
            arr = np.asarray(b)
            return arr
        df = self.to_pandas()
        if column:
            return df[column].to_numpy()
        return {c: df[c].to_numpy() for c in df.columns}

    def to_pandas(self):
        import pandas as pd
        b = self._b
        if isinstance(b, pd.DataFrame):
            return b
        if isinstance(b, np.ndarray):
            return pd.DataFrame(b) if b.ndim > 1 \
                else pd.DataFrame({"value": b})
        try:
            import pyarrow as pa
            if isinstance(b, pa.Table):
                from ray_tpu.air.util.tensor_extensions import (
                    is_tensor_type, tensor_column_to_numpy)
                if any(is_tensor_type(f.type) for f in b.schema):
                    cols = {}
                    for name in b.column_names:
                        col = b.column(name)
                        if is_tensor_type(col.type):
                            nd = tensor_column_to_numpy(col)
                            cols[name] = pd.Series(list(nd),
                                                   dtype=object)
                        else:
                            cols[name] = col.to_pandas()
                    return pd.DataFrame(cols)
                return b.to_pandas()
        except ImportError:
            pass
        if isinstance(b, dict):
            cols = {}
            for k, v in b.items():
                arr = np.asarray(v)
                # Tensor columns (ndim > 1) become object Series of
                # per-row ndarrays in the pandas view.
                cols[k] = (pd.Series(list(arr), dtype=object)
                           if arr.ndim > 1 else arr)
            return pd.DataFrame(cols)
        if b and isinstance(b[0], dict):
            return pd.DataFrame(b)
        return pd.DataFrame({"value": b})

    def to_arrow(self):
        import pyarrow as pa
        b = self._b
        if isinstance(b, pa.Table):
            return b
        if isinstance(b, dict):
            # Multi-dimensional columns become fixed-shape tensor
            # extension columns (reference: air/util/tensor_extensions/
            # arrow.py ArrowTensorArray) instead of object-dtype rows.
            from ray_tpu.air.util.tensor_extensions import (
                ArrowTensorArray)
            names, arrays = [], []
            for k, v in b.items():
                arr = np.asarray(v)
                names.append(k)
                arrays.append(ArrowTensorArray.from_numpy(arr)
                              if arr.ndim > 1 else pa.array(arr))
            return pa.Table.from_arrays(arrays, names=names)
        return pa.Table.from_pandas(self.to_pandas(),
                                    preserve_index=False)

    def to_batch_format(self, batch_format: Optional[str]):
        if batch_format in (None, "default", "native"):
            return self._b
        if batch_format == "numpy":
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format == "pyarrow":
            return self.to_arrow()
        if batch_format == "pylist":
            return self.to_pylist()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # -- manipulation --------------------------------------------------
    def slice(self, start: int, end: int) -> Any:
        b = self._b
        if isinstance(b, list):
            return b[start:end]
        if isinstance(b, dict):
            return {k: np.asarray(v)[start:end] for k, v in b.items()}
        try:
            import pyarrow as pa
            if isinstance(b, pa.Table):
                return b.slice(start, end - start)
        except ImportError:
            pass
        return b.iloc[start:end]

    @staticmethod
    def combine(blocks: List[Any]) -> Any:
        blocks = [b for b in blocks
                  if BlockAccessor(b).num_rows() > 0] or blocks[:1]
        first = blocks[0]
        if isinstance(first, list):
            out = []
            for b in blocks:
                out.extend(b)
            return out
        if isinstance(first, dict):
            keys = first.keys()
            return {k: np.concatenate([np.asarray(b[k]) for b in blocks])
                    for k in keys}
        if isinstance(first, np.ndarray):
            return np.concatenate(blocks)
        try:
            import pyarrow as pa
            if isinstance(first, pa.Table):
                return pa.concat_tables(blocks)
        except ImportError:
            pass
        import pandas as pd
        return pd.concat(blocks, ignore_index=True)

    @staticmethod
    def empty_like(block: Any) -> Any:
        return BlockAccessor(block).slice(0, 0)
