"""Proactive object replication (the push half of the object manager).

Reference: src/ray/object_manager/push_manager.h — the reference pushes
task args/returns to nodes known to need them instead of waiting for N
cold pulls.  Here the same machinery is exposed for broadcast-shaped
flows: ``push_object(ref)`` streams the object's chunks from this node's
raylet to every (or selected) peer raylet, so subsequent reads there are
local.  Inline objects (≤ the inline threshold) travel inside specs and
need no push.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.object_ref import ObjectRef


def push_object(ref: ObjectRef,
                node_ids: Optional[List] = None) -> Dict:
    """Replicate a shm-store object to peer nodes ahead of demand.

    node_ids: node-id hex strings (or NodeID objects) to push to;
    None = every other alive node.  Returns {"pushed": [...node id
    hex], "failed": [...node id hex]}."""
    w = worker_mod.global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu.init() must be called first")
    if w.raylet is None:
        raise RuntimeError("no raylet connection (local mode?)")
    from ray_tpu._private.gcs_client import global_gcs_client
    wanted = None
    if node_ids is not None:
        wanted = {n.hex() if hasattr(n, "hex") else str(n)
                  for n in node_ids}
    my_addr = tuple(w.raylet_addr) if w.raylet_addr else None
    targets = []
    for view in global_gcs_client().nodes.get_all():
        if not view["alive"]:
            continue
        if tuple(view["addr"]) == my_addr:
            continue
        if wanted is not None and view["node_id"].hex() not in wanted:
            continue
        targets.append(view["node_id"])
    if not targets:
        return {"pushed": [], "failed": []}
    return w._run(w.raylet.request(
        "os_push_to", {"oid": ref.id.binary(), "targets": targets},
        timeout=300))
