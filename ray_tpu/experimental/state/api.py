"""State observability API: list/summarize cluster entities.

Reference: python/ray/experimental/state/api.py — list_actors (:719),
list_nodes (:810), list_tasks (:942), list_objects (:986),
summarize_* (:1233+), backed by the GCS plus per-node raylet state feeds.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private import worker as worker_mod


def _w():
    w = worker_mod.global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu.init() must be called first")
    return w


def _client():
    """Typed accessor facade (reference: accessor.h / the
    GlobalStateAccessor that backs these state APIs)."""
    from ray_tpu._private.gcs_client import global_gcs_client
    return global_gcs_client()


def _gcs(method: str, body: Optional[dict] = None):
    w = _w()
    return w._run(w._gcs_request(method, body or {}))


def list_nodes() -> List[Dict]:
    out = []
    for v in _client().nodes.get_all():
        out.append({
            "node_id": v["node_id"].hex(),
            "state": "ALIVE" if v["alive"] else "DEAD",
            "address": list(v["addr"]),
            "resources_total": v["resources"],
            "resources_available": v.get("available", {}),
            "labels": v.get("labels", {}),
            "node_stats": v.get("node_stats", {}),
        })
    return out


def list_actors(detail: bool = False) -> List[Dict]:
    out = []
    for v in _client().actors.list():
        row = {
            "actor_id": v["actor_id"].hex(),
            "state": v["state"],
            "class_name": v.get("class_name"),
            "name": v.get("name"),
            "node_id": v["node_id"].hex() if v.get("node_id") else None,
            "pid": v.get("pid"),
        }
        if detail:
            row.update({"num_restarts": v.get("num_restarts", 0),
                        "death_cause": v.get("death_cause")})
        out.append(row)
    return out


def list_placement_groups() -> List[Dict]:
    out = []
    for v in _client().placement_groups.list():
        out.append({
            "placement_group_id": v["pg_id"].hex(),
            "state": v["state"],
            "name": v.get("name"),
            "bundles": v["bundles"],
        })
    return out


def list_jobs() -> List[Dict]:
    return _client().jobs.list()


async def _fanout(method: str) -> List[dict]:
    """One RPC to every alive raylet."""
    import asyncio
    from ray_tpu._private import protocol
    w = _w()
    nodes = await w._gcs_request("get_nodes", {})
    replies = []

    async def _one(view):
        try:
            conn = await protocol.Connection.connect(
                view["addr"][0], view["addr"][1], name="state-api",
                timeout=10)
            try:
                return await conn.request(method, {}, timeout=10)
            finally:
                await conn.close()
        except Exception:
            return None

    replies = await asyncio.gather(
        *[_one(v) for v in nodes if v.get("alive")])
    return [r for r in replies if r is not None]


def list_tasks() -> List[Dict]:
    w = _w()
    out = []
    for reply in w._run(_fanout("list_leases")):
        for r in reply["running"]:
            r["node_id"] = reply["node_id"]
            r["type"] = "ACTOR_TASK" if r.get("actor_id") else "NORMAL_TASK"
            out.append(r)
        for q in reply["queued"]:
            q["node_id"] = reply["node_id"]
            q["type"] = "NORMAL_TASK"
            out.append(q)
    return out


def list_objects() -> List[Dict]:
    w = _w()
    out = []
    for reply in w._run(_fanout("list_local_objects")):
        for o in reply["objects"]:
            o["node_id"] = reply["node_id"]
            out.append(o)
    return out


def list_cluster_events(limit: int = 200) -> List[Dict]:
    """Structured cluster events: node deaths, actor restarts/deaths
    (reference: dashboard/modules/event + src/ray/util/event.h)."""
    return _client().events.list(limit=limit)


def summarize_tasks() -> Dict:
    counts: Dict[str, int] = {}
    for t in list_tasks():
        counts[t["state"]] = counts.get(t["state"], 0) + 1
    return {"by_state": counts, "total": sum(counts.values())}


def summarize_objects() -> Dict:
    objs = list_objects()
    total = sum(o["size"] for o in objs)
    by_where: Dict[str, int] = {}
    for o in objs:
        by_where[o["where"]] = by_where.get(o["where"], 0) + o["size"]
    return {"total_objects": len(objs), "total_bytes": total,
            "bytes_by_location": by_where}
