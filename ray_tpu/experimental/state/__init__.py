from ray_tpu.experimental.state.api import (  # noqa: F401
    list_cluster_events,
    list_actors,
    list_jobs,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    summarize_objects,
    summarize_tasks,
)
