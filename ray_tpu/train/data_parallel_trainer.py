"""DataParallelTrainer: N rank-labeled workers run the user's
train_loop_per_worker; results stream back through the session.

Reference: python/ray/train/data_parallel_trainer.py:52 + the call stack in
SURVEY.md §3.4 (BackendExecutor.start -> WorkerGroup -> Backend.on_start ->
start_training -> session.report relay to Tune).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ray_tpu.air import session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.base_trainer import BaseTrainer
from ray_tpu.train._internal.backend_executor import BackendExecutor


class DataParallelTrainer(BaseTrainer):
    _backend_config_cls = BackendConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict] = None,
                 dataset_config: Optional[Dict] = None,
                 preprocessor=None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self._train_loop = train_loop_per_worker
        self._train_loop_config = train_loop_config or {}
        self._backend_config = backend_config or self._backend_config_cls()
        self._datasets = datasets or {}
        self._dataset_config = dataset_config or {}
        self._preprocessor = preprocessor

    def _prepared_datasets(self) -> Dict:
        """Apply DatasetConfig roles: fit the preprocessor on fit=True
        datasets, transform transform=True ones, shuffle global_shuffle
        ones; returns {name: (dataset, split?, ingest_opts)} (reference:
        data_parallel_trainer dataset ingest + preprocessor fitting in
        BaseTrainer.preprocess_datasets).

        With the streaming data plane on (RT_DATA_STREAMING=1),
        global_shuffle datasets are NOT shuffled eagerly here: each
        rank's shard reshuffles per epoch through the streaming
        executor (train/ingest.py StreamingDatasetShard), so the
        shuffle's windows overlap the step loop instead of stalling
        epoch boundaries."""
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg
        from ray_tpu.air.config import DatasetConfig
        merged = DatasetConfig.validated(self._dataset_config,
                                         self._datasets)
        out = {}
        pp = self._preprocessor
        if pp is not None:
            for name, ds in self._datasets.items():
                if merged[name].fit:
                    pp.fit(ds)
                    break
        for name, ds in self._datasets.items():
            dc = merged[name]
            if pp is not None and dc.transform:
                ds = pp.transform(ds)
            # A USER-pended all-to-all (streaming random_shuffle called
            # before handing the dataset over) must materialize ONCE
            # here: every rank's split() would otherwise re-run the
            # whole dataset-sized exchange for identical output.
            from ray_tpu.data._internal.operators import AllToAllOp
            if any(isinstance(s[0], AllToAllOp)
                   for s in getattr(ds, "_stages", ())):
                ds._execute()
            ingest = None
            if dc.global_shuffle:
                if cfg.data_streaming:
                    seed = dc.shuffle_seed
                    if seed is None:
                        # Drawn ONCE on the driver: every rank must
                        # share the epoch order (a split=False dataset
                        # arrives whole on all ranks, and per-rank
                        # random seeds would silently desync lockstep
                        # consumers; the legacy path shuffled once).
                        import random
                        seed = random.randrange(1 << 30)
                    ingest = {"shuffle_each_epoch": True,
                              "shuffle_seed": seed}
                else:
                    ds = ds.random_shuffle(seed=dc.shuffle_seed)
            out[name] = (ds, bool(dc.split), ingest)
        return out

    def training_loop(self) -> None:
        from ray_tpu.train._internal.backend_executor import (
            TrainingWorkerError)
        fc = self.run_config.failure_config
        # The gang-restart budget: FailureConfig.max_failures if the user
        # set one, else 3 (reference: BackendExecutor default retries).
        # Distinct from Tune trial retries — a gang restart resumes from
        # the last in-trial checkpoint WITHOUT restarting the trial.
        # With ScalingConfig(elastic=True) this budget counts COLD
        # restarts only: in-place elastic re-forms are absorbed inside
        # executor.get_next_results and never raise TrainingWorkerError
        # unless the re-form itself failed (quorum loss / deadline /
        # re-shard fault) — only that fallback consumes a unit here.
        budget = fc.max_failures if fc is not None else 3
        executor = BackendExecutor(self._backend_config,
                                   self.scaling_config)
        latest_ckpt = self.resume_from_checkpoint
        started = restart_pending = False
        # Fit/transform/shuffle ONCE: gang restarts reuse the prepared
        # datasets (inputs don't change across restarts).
        prepared = self._prepared_datasets() if self._datasets else None
        try:
            while True:
                try:
                    if restart_pending:
                        executor.restart()
                        restart_pending = False
                    if not started:
                        executor.start()
                        started = True
                    config = dict(self._train_loop_config)
                    if prepared is not None:
                        config["__datasets__"] = dict(prepared)
                    executor.start_training(
                        self._train_loop, config, checkpoint=latest_ckpt,
                        trial_name=session.get_trial_name(),
                        trial_id=session.get_trial_id())
                    while True:
                        results = executor.get_next_results()
                        if results is None:
                            break
                        # rank 0 is authoritative for metrics/checkpoint
                        # (reference: data_parallel_trainer result
                        # aggregation).
                        if results[0].checkpoint is not None:
                            latest_ckpt = results[0].checkpoint
                        session.report(results[0].metrics,
                                       checkpoint=results[0].checkpoint)
                    executor.finish_training()
                    return
                except TrainingWorkerError as e:
                    # budget semantics: -1 = unlimited (reference
                    # FailureConfig convention), 0 = fail fast.
                    if budget == 0:
                        raise
                    if budget > 0:
                        budget -= 1
                    import logging
                    logging.getLogger(__name__).warning(
                        "train gang worker died (%s); restarting gang "
                        "from last checkpoint (%s restarts left)",
                        e, "inf" if budget < 0 else budget)
                    # The restart itself runs at the TOP of the loop so a
                    # failure during recovery consumes budget too instead
                    # of escaping the retry path.
                    if started:
                        restart_pending = True
        finally:
            executor.shutdown()
