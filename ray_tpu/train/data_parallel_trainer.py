"""DataParallelTrainer: N rank-labeled workers run the user's
train_loop_per_worker; results stream back through the session.

Reference: python/ray/train/data_parallel_trainer.py:52 + the call stack in
SURVEY.md §3.4 (BackendExecutor.start -> WorkerGroup -> Backend.on_start ->
start_training -> session.report relay to Tune).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ray_tpu.air import session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.base_trainer import BaseTrainer
from ray_tpu.train._internal.backend_executor import BackendExecutor


class DataParallelTrainer(BaseTrainer):
    _backend_config_cls = BackendConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self._train_loop = train_loop_per_worker
        self._train_loop_config = train_loop_config or {}
        self._backend_config = backend_config or self._backend_config_cls()
        self._datasets = datasets or {}

    def training_loop(self) -> None:
        executor = BackendExecutor(self._backend_config,
                                   self.scaling_config)
        executor.start()
        try:
            train_fn = self._train_loop
            config = dict(self._train_loop_config)
            if self._datasets:
                config["__datasets__"] = {
                    name: ds for name, ds in self._datasets.items()}
            executor.start_training(
                train_fn, config, checkpoint=self.resume_from_checkpoint,
                trial_name=session.get_trial_name(),
                trial_id=session.get_trial_id())
            while True:
                results = executor.get_next_results()
                if results is None:
                    break
                # rank 0 is authoritative for metrics/checkpoint
                # (reference: data_parallel_trainer result aggregation).
                session.report(results[0].metrics,
                               checkpoint=results[0].checkpoint)
            executor.finish_training()
        finally:
            executor.shutdown()
