"""Distributed training (the reference's Ray Train, SURVEY.md §2.3/3.4).

A gang of rank-labeled worker actors in a placement group runs the user's
train_loop_per_worker; the JaxBackend wires the jax coordination service +
device mesh (the NCCL-process-group replacement); results/checkpoints
stream back through the session to Tune, which executes the run.
"""

from ray_tpu.train.backend import Backend, BackendConfig  # noqa: F401
from ray_tpu.train.base_trainer import (  # noqa: F401
    BaseTrainer, TrainingFailedError,
)
from ray_tpu.train.data_parallel_trainer import (  # noqa: F401
    DataParallelTrainer,
)
from ray_tpu.train.jax import JaxConfig, JaxTrainer  # noqa: F401
from ray_tpu.train.gbdt import (  # noqa: F401
    GBDTBoosterModel, GBDTTrainer, XGBoostTrainer)
from ray_tpu.train.collective import (  # noqa: F401
    GradientSynchronizer, allreduce_gradients,
)
from ray_tpu.train.elastic import ElasticReset  # noqa: F401

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("train")
del _rlu
