"""Backend/BackendConfig: per-framework worker-gang setup hooks.

Reference: python/ray/train/backend.py:15,27 (Backend.on_start/on_shutdown
run framework process-group setup, e.g. torch dist.init_process_group in
train/torch/config.py:54).  TPU-era: the JaxBackend wires the jax
coordination service + device mesh instead of NCCL (SURVEY.md §5
"distributed communication backend").
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    def on_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_training_start(self, worker_group, backend_config: BackendConfig):
        pass

    def on_shutdown(self, worker_group, backend_config: BackendConfig):
        pass
