"""TorchTrainer: CPU-torch DDP over a gang of worker actors.

Reference: python/ray/train/torch/torch_trainer.py:15 + config.py:54
(_setup_torch_process_group: rendezvous env + dist.init_process_group).
On this framework torch is the CPU sidecar (the TPU path is JaxTrainer);
the gloo process group rides the same gang the JaxBackend uses, proving
the Backend seam is framework-agnostic.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer


@dataclasses.dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"
    init_timeout_s: float = 120.0

    @property
    def backend_cls(self):
        return _TorchBackend


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _setup_group(rank: int, world: int, addr: str, port: int,
                 backend: str, timeout_s: float):
    import datetime
    import os

    import torch.distributed as dist
    os.environ["MASTER_ADDR"] = addr
    os.environ["MASTER_PORT"] = str(port)
    if not dist.is_initialized():
        dist.init_process_group(
            backend, rank=rank, world_size=world,
            timeout=datetime.timedelta(seconds=timeout_s))
    return True


def _teardown_group():
    import torch.distributed as dist
    if dist.is_initialized():
        dist.destroy_process_group()
    return True


class _TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: TorchConfig):
        import ray_tpu
        # Rendezvous on rank 0's host (reference: config.py:54 picks the
        # master from worker 0's metadata).
        info = worker_group.execute_single(0, _node_ip_and_port)
        addr, port = info
        world = worker_group.num_workers
        refs = [
            w.execute.remote(_setup_group, rank, world, addr, port,
                             backend_config.backend,
                             backend_config.init_timeout_s)
            for rank, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(refs, timeout=backend_config.init_timeout_s + 60)

    def on_shutdown(self, worker_group, backend_config: TorchConfig):
        import ray_tpu
        try:
            ray_tpu.get([w.execute.remote(_teardown_group)
                         for w in worker_group.workers], timeout=30)
        except Exception:
            pass


def _node_ip_and_port():
    return ("127.0.0.1", _free_port())


def prepare_model(model):
    """Wrap in DDP when the group spans >1 rank (reference:
    train/torch/train_loop_utils.py:49 prepare_model)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel
    if dist.is_initialized() and dist.get_world_size() > 1:
        return DistributedDataParallel(model)
    return model


class _EpochAdvancingLoader:
    """DataLoader wrapper that bumps the DistributedSampler epoch on
    every __iter__ — without it, the sampler permutes from (seed, 0)
    forever and every epoch sees the same order (reference:
    train_loop_utils.py _WrappedDataLoader's set_epoch handling)."""

    def __init__(self, loader, sampler):
        self._loader = loader
        self._sampler = sampler
        self._epoch = 0

    def __iter__(self):
        self._sampler.set_epoch(self._epoch)
        self._epoch += 1
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)

    def __getattr__(self, name):
        return getattr(self._loader, name)


def prepare_data_loader(data_loader, *, add_dist_sampler: bool = True):
    """Shard a DataLoader across the gang with a DistributedSampler
    (reference: train/torch/train_loop_utils.py:262
    prepare_data_loader).  No-op for single-rank groups or loaders that
    already carry a DistributedSampler.  The returned loader advances
    the sampler epoch on every __iter__ so shuffle order differs per
    epoch."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader, SequentialSampler
    from torch.utils.data.distributed import DistributedSampler
    if not (dist.is_initialized() and dist.get_world_size() > 1
            and add_dist_sampler):
        return data_loader
    sampler = getattr(data_loader, "sampler", None)
    if isinstance(sampler, DistributedSampler):
        return data_loader
    if data_loader.batch_size is None:
        # batch_sampler loaders report batch_size=None; rebuilding one
        # with a plain sampler would silently yield UNBATCHED samples.
        raise ValueError(
            "prepare_data_loader cannot shard a DataLoader built with "
            "batch_sampler= (its batching logic cannot be transplanted "
            "onto a DistributedSampler); construct the per-rank loader "
            "yourself, e.g. over a DistributedSampler of your dataset")
    dist_sampler = DistributedSampler(
        data_loader.dataset, num_replicas=dist.get_world_size(),
        rank=dist.get_rank(),
        shuffle=not isinstance(sampler, SequentialSampler))
    kwargs = dict(
        batch_size=data_loader.batch_size, sampler=dist_sampler,
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        pin_memory=data_loader.pin_memory,
        drop_last=data_loader.drop_last,
        timeout=data_loader.timeout,
        worker_init_fn=data_loader.worker_init_fn,
        generator=data_loader.generator,
        persistent_workers=data_loader.persistent_workers)
    if data_loader.num_workers > 0:
        kwargs["prefetch_factor"] = data_loader.prefetch_factor
    return _EpochAdvancingLoader(DataLoader(data_loader.dataset,
                                            **kwargs), dist_sampler)


class TorchTrainer(DataParallelTrainer):
    _backend_config_cls = TorchConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 torch_config: Optional[TorchConfig] = None,
                 **kwargs):
        super().__init__(train_loop_per_worker,
                         backend_config=torch_config or TorchConfig(),
                         **kwargs)
