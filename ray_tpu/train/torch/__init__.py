from ray_tpu.train.torch.torch_trainer import (  # noqa: F401
    TorchConfig,
    TorchTrainer,
    prepare_data_loader,
    prepare_model,
)
