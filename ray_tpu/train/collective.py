"""Data-parallel gradient synchronization on the collective fast plane.

The host-side twin of in-graph XLA gradient reduction (which
ray_tpu.parallel compiles over ICI): when gradients live on host —
numpy optim states, GBDT statistics, CPU reference training — this
module buckets them (``util.collective.fuse_buckets``) and allreduces
the buckets asynchronously over the peer-to-peer transfer plane, so
many small tensors ride a handful of fused exchanges and communication
overlaps the caller's unpacking work.

Works with the gang collective group the BackendExecutor creates
automatically (``session.get_collective_group()``); pass ``group_name``
to use an explicitly-managed group instead.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_tpu._private import failpoints


def _step_failpoint():
    """Chaos hook at the gradient-sync entry (the canonical
    mid-epoch interruption point: the member is between backward and
    optimizer update).  ``kill`` SIGKILLs the worker process — the
    gang's death watch turns that into CollectiveGroupError at every
    survivor within a round trip."""
    if not failpoints.ACTIVE:
        return
    rank = os.environ.get("RT_TRAIN_WORLD_RANK", "0")
    act = failpoints.check("train.step", peer=f"r{rank}")
    if act is None:
        return
    if act.kind == "kill":
        os._exit(int(act.arg or 1))
    if act.kind == "error":
        from ray_tpu.train.elastic import ElasticReset
        raise ElasticReset(f"failpoint: injected step fault at rank {rank}")
    if act.kind == "delay":
        import time
        time.sleep(act.delay_s)


def allreduce_gradients(grads, *, group_name: Optional[str] = None,
                        average: bool = True,
                        bucket_bytes: Optional[int] = None):
    """Sum (and by default average) gradients across the training gang.

    ``grads`` may be a dict (synced in sorted-key order so every rank
    fuses identically), a list/tuple, or a single array; the reduced
    values are written back in place where possible and returned in the
    input's shape.  Single-worker runs (no gang group) return the input
    unchanged (averaging by world size 1)."""
    from ray_tpu.air import session
    from ray_tpu.util import collective as col

    _step_failpoint()
    if group_name is None:
        try:
            group_name = session.get_collective_group()
        except Exception:
            group_name = None
    if group_name is None:
        return grads

    if isinstance(grads, dict):
        keys = sorted(grads)
        tensors = [np.ascontiguousarray(grads[k]) for k in keys]
    elif isinstance(grads, (list, tuple)):
        keys = None
        tensors = [np.ascontiguousarray(g) for g in grads]
    else:
        keys = None
        tensors = [np.ascontiguousarray(grads)]

    reduced = col.allreduce_coalesced(tensors, group_name=group_name,
                                      bucket_bytes=bucket_bytes)
    if average:
        world = col.get_group_handle(group_name).world_size
        if world > 1:
            for t in reduced:
                # Integer tensors (counts, histograms-as-ints) stay
                # SUMMED — true division can't land in an int output.
                if np.issubdtype(t.dtype, np.inexact):
                    np.divide(t, world, out=t)

    if isinstance(grads, dict):
        return {k: t for k, t in zip(keys, reduced)}
    if isinstance(grads, tuple):
        return tuple(reduced)
    if isinstance(grads, list):
        return reduced
    return reduced[0]


class GradientSynchronizer:
    """Gradient-hook overlap: allreduce buckets WHILE backward still
    runs, instead of syncing everything after the step.

    ``allreduce_gradients`` needs the full gradient set up front, so
    the whole exchange serializes behind backward.  This class takes
    gradients one at a time, as the user's backward produces them
    (reverse-topological — the order autograd hooks fire), fills fixed
    buckets, and submits each bucket's fused allreduce the moment it is
    full.  Communication of early (late-layer) buckets hides under the
    compute of earlier layers; ``finish()`` only waits for the tail.

        sync = GradientSynchronizer()
        for step in ...:
            for name, g in backward_in_reverse(...):   # hook order
                sync.grad_ready(name, g)
            grads = sync.finish()                       # averaged
            apply(grads)

    The bucket plan is fixed from the FIRST step's arrival order and
    reused verbatim afterwards, so every step submits the identical op
    sequence (the group contract).  All ranks must therefore feed the
    same parameters in the same order — true whenever they run the
    same model graph; a divergent order fails the group's rendezvous
    signature check with a structured mismatch error rather than
    corrupting data.  Later steps tolerate out-of-plan-order arrivals:
    a bucket is submitted only once it AND every earlier bucket are
    full, preserving launch order.

    Elastic training: the group is re-resolved every step from
    ``session.get_collective_group()``, so a synchronizer survives a
    re-form (the re-entered loop sees the new group name).  A
    re-formation mid-step fails in-flight bucket waits with
    CollectiveGroupError; the per-step state is reset before the error
    propagates, so the re-entered loop starts from a clean step."""

    def __init__(self, *, group_name: Optional[str] = None,
                 average: bool = True,
                 bucket_bytes: Optional[int] = None):
        from ray_tpu._private.config import GLOBAL_CONFIG as cfg
        self._group_arg = group_name
        self._average = average
        self._bucket_bytes = int(bucket_bytes
                                 or cfg.collective_bucket_bytes)
        self._plan: Optional[List[List[str]]] = None  # sealed name lists
        self._slot: Dict[str, Tuple[int, int]] = {}
        self._reset_step()

    def _reset_step(self):
        if not self._slot:
            # The plan never froze (first step aborted): rebuild it
            # from scratch next step rather than keep a partial one.
            self._plan = None
        self._started = False
        self._group: Optional[str] = None
        self._step_grads: Dict[str, np.ndarray] = {}
        self._filled: List[int] = []
        self._fired = 0
        self._works: list = []        # (names, CollectiveWork)
        self._open: List[str] = []    # first step: names in the open bucket
        self._open_bytes = 0
        self._open_dtype = None

    # -- internals -----------------------------------------------------
    def _submit(self, names: List[str]):
        from ray_tpu.util import collective as col
        bucket = col.CollectiveBucket(
            [self._step_grads[n] for n in names])
        self._works.append(
            (names, bucket.allreduce_async(group_name=self._group)))

    def _seal_open(self):
        if not self._open:
            return
        names, self._open = self._open, []
        self._open_bytes, self._open_dtype = 0, None
        self._plan.append(names)
        self._submit(names)

    def _fire_ready(self):
        while self._fired < len(self._plan) and \
                self._filled[self._fired] == len(self._plan[self._fired]):
            self._submit(self._plan[self._fired])
            self._fired += 1

    # -- public API ----------------------------------------------------
    def grad_ready(self, name: str, grad) -> None:
        """Hand over one parameter's gradient as backward produces it.
        May start a fused allreduce; never blocks on one."""
        if not self._started:
            self._started = True
            _step_failpoint()
            if self._plan is not None:
                self._filled = [0] * len(self._plan)
            if self._group_arg is not None:
                self._group = self._group_arg
            else:
                try:
                    from ray_tpu.air import session
                    self._group = session.get_collective_group()
                except Exception:
                    self._group = None
        if name in self._step_grads:
            raise ValueError(f"gradient {name!r} fed twice this step")
        arr = np.ascontiguousarray(grad)
        self._step_grads[name] = arr
        if self._group is None:
            return  # single-worker / no gang group: passthrough
        try:
            if self._slot:
                slot = self._slot.get(name)
                if slot is None:
                    raise ValueError(
                        f"unknown gradient {name!r}: the bucket plan "
                        "was fixed on the first step (create a new "
                        "GradientSynchronizer if the model changed)")
                self._filled[slot[0]] += 1
                self._fire_ready()
            else:
                # First step: grow the open bucket in arrival order,
                # seal+submit at the byte threshold or a dtype change
                # (buckets are dtype-homogeneous).
                if self._open and (arr.dtype != self._open_dtype
                                   or self._open_bytes + arr.nbytes
                                   > self._bucket_bytes):
                    self._seal_open()
                if self._plan is None:
                    self._plan = []
                if not self._open:
                    self._open_dtype = arr.dtype
                self._open.append(name)
                self._open_bytes += arr.nbytes
        except BaseException:
            self._reset_step()
            raise

    def finish(self) -> Dict[str, np.ndarray]:
        """Wait for the in-flight buckets (submission order), average,
        and return {name: reduced gradient} (reduced in place where the
        input arrays were writable).  Resets for the next step."""
        from ray_tpu.util import collective as col
        if not self._started:
            return {}
        try:
            if self._group is None:
                out = self._step_grads
                self._reset_step()
                return out
            if not self._slot:
                # Still on the first step: seal the tail bucket and
                # freeze the plan for every later step.
                self._seal_open()
                self._slot = {n: (b, s)
                              for b, names in enumerate(self._plan or [])
                              for s, n in enumerate(names)}
            else:
                missing = [n for n in self._slot
                           if n not in self._step_grads]
                if missing:
                    raise ValueError(
                        "finish() before every gradient arrived "
                        f"(missing: {sorted(missing)[:5]})")
            out: Dict[str, np.ndarray] = {}
            for names, work in self._works:
                for n, t in zip(names, work.wait()):
                    out[n] = t
            if self._average:
                world = col.get_group_handle(self._group).world_size
                if world > 1:
                    for t in out.values():
                        if np.issubdtype(t.dtype, np.inexact):
                            np.divide(t, world, out=t)
            return out
        finally:
            self._reset_step()
