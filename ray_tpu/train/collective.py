"""Data-parallel gradient synchronization on the collective fast plane.

The host-side twin of in-graph XLA gradient reduction (which
ray_tpu.parallel compiles over ICI): when gradients live on host —
numpy optim states, GBDT statistics, CPU reference training — this
module buckets them (``util.collective.fuse_buckets``) and allreduces
the buckets asynchronously over the peer-to-peer transfer plane, so
many small tensors ride a handful of fused exchanges and communication
overlaps the caller's unpacking work.

Works with the gang collective group the BackendExecutor creates
automatically (``session.get_collective_group()``); pass ``group_name``
to use an explicitly-managed group instead.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def allreduce_gradients(grads, *, group_name: Optional[str] = None,
                        average: bool = True,
                        bucket_bytes: Optional[int] = None):
    """Sum (and by default average) gradients across the training gang.

    ``grads`` may be a dict (synced in sorted-key order so every rank
    fuses identically), a list/tuple, or a single array; the reduced
    values are written back in place where possible and returned in the
    input's shape.  Single-worker runs (no gang group) return the input
    unchanged (averaging by world size 1)."""
    from ray_tpu.air import session
    from ray_tpu.util import collective as col

    if group_name is None:
        try:
            group_name = session.get_collective_group()
        except Exception:
            group_name = None
    if group_name is None:
        return grads

    if isinstance(grads, dict):
        keys = sorted(grads)
        tensors = [np.ascontiguousarray(grads[k]) for k in keys]
    elif isinstance(grads, (list, tuple)):
        keys = None
        tensors = [np.ascontiguousarray(g) for g in grads]
    else:
        keys = None
        tensors = [np.ascontiguousarray(grads)]

    reduced = col.allreduce_coalesced(tensors, group_name=group_name,
                                      bucket_bytes=bucket_bytes)
    if average:
        world = col.get_group_handle(group_name).world_size
        if world > 1:
            for t in reduced:
                # Integer tensors (counts, histograms-as-ints) stay
                # SUMMED — true division can't land in an int output.
                if np.issubdtype(t.dtype, np.inexact):
                    np.divide(t, world, out=t)

    if isinstance(grads, dict):
        return {k: t for k, t in zip(keys, reduced)}
    if isinstance(grads, tuple):
        return tuple(reduced)
    if isinstance(grads, list):
        return reduced
    return reduced[0]
