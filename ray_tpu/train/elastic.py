"""Elastic gang recovery: re-form the training collective at a new
world size instead of cold-restarting the trial.

When a gang member dies (the collective plane's death watch aborts the
group, so every survivor's in-flight op raises CollectiveGroupError
within a round trip) or the driver grants a resize, survivors
rendezvous a fresh group incarnation through a per-gang named
**elastic coordinator** actor:

    worker:  break -> report_break -> wait_reform -> init new group
             -> state sync (re-shard) -> re-enter train_fn
    driver:  begin_recovery -> collect breaks (settle window, bounded
             by RT_TRAIN_REFORM_TIMEOUT_S + jitter) -> quorum check
             -> assign compact ranks -> arm death watch -> post_reform
             -> await reform_done from every rank

State sync broadcasts the authoritative survivor's in-memory stash
(``session.stash_elastic_state``) to every member over the collective
data plane (one-sided reads / blob frames for large states — no
checkpoint round trip).  Authoritative = the *lowest committed step*
among stash holders (lowest rank tiebreak): the least-advanced
survivor's state is the only one every rank is guaranteed to have
contributed to, so all ranks roll back to it and the loss curve stays
continuous.  Adoption is atomic per worker (full deserialize, then one
reference swap) — a death mid-re-shard aborts the new group, every
survivor's sync raises, and the driver falls back to the last
checkpoint; a torn optimizer state is structurally impossible.

The driver coordinates ONLY through the elastic coordinator — never
through worker-actor RPCs: a worker's actor methods ride a serial
thread pool that a blocked ``next_result`` would head-of-line block.
"""

from __future__ import annotations

import logging
import os
import pickle
import queue
import threading
import time

import numpy as np

import ray_tpu
from ray_tpu._private import failpoints
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu.util.collective.types import CollectiveGroupError

logger = logging.getLogger(__name__)

_ELASTIC_PREFIX = "_rt_train_elastic::"

# Flush marker a surviving rejoin enqueues on its report queue.  The
# driver discards exactly one in-flight next_result per member when it
# drops the interrupted round, and that call consumes exactly one queue
# item whenever it runs — the marker is that item, so discarded refs
# never eat a real post-reform report (which would skew per-rank report
# counts and trip the driver's even-reporting invariant).  A marker the
# stale call did NOT eat (it had already consumed a pre-break report)
# reaches the driver, which skips it and re-polls that worker alone.
FLUSH = "__rt_elastic_flush__"


class ElasticReset(Exception):
    """The gang broke (member death / resize grant): unwind the user
    train loop so the worker can rejoin the re-formed group.  Raised
    out of ``session.report`` and the gradient-sync entry points; user
    loops should let it propagate."""


class _ElasticCoordinator:
    """Async named actor: the per-gang reform rendezvous.

    One *generation* per successful re-form (gen 0 = the original
    gang).  Workers report breaks against their current generation;
    the driver posts instructions for the next one.  A fresh
    coordinator is created per gang incarnation (cold restarts get a
    new name), so no cross-incarnation state can leak."""

    def __init__(self):
        import asyncio
        self._cond = asyncio.Condition()
        self._recovery_gen = 0        # highest recovery announced
        self._breaks: dict = {}       # gen -> {old_rank: info}
        self._reform: dict | None = None   # latest instruction (or abort)
        self._done: dict = {}         # gen -> {rank: [ok, err]}

    # -- worker side ---------------------------------------------------
    async def wait_signal(self, after_gen: int):
        """Long-poll for a recovery announcement newer than
        ``after_gen`` (the worker agent thread uses this to wake a
        loop thread blocked in session.report)."""
        async with self._cond:
            while self._recovery_gen <= after_gen:
                await self._cond.wait()
            return self._recovery_gen

    async def report_break(self, gen: int, old_rank: int, info: dict):
        async with self._cond:
            self._breaks.setdefault(gen, {})[int(old_rank)] = info
            self._cond.notify_all()
        return True

    async def wait_reform(self, gen: int):
        """Block until the driver posts instructions superseding the
        caller's generation."""
        async with self._cond:
            while self._reform is None or self._reform["gen"] <= gen:
                await self._cond.wait()
            return dict(self._reform)

    async def report_reform_done(self, gen: int, rank: int, ok: bool,
                                 err: str | None = None):
        async with self._cond:
            self._done.setdefault(gen, {})[int(rank)] = [bool(ok), err]
            self._cond.notify_all()
        return True

    # -- driver side ---------------------------------------------------
    async def begin_recovery(self, gen: int):
        async with self._cond:
            if gen > self._recovery_gen:
                self._recovery_gen = gen
            self._cond.notify_all()
        return True

    async def breaks(self, gen: int):
        async with self._cond:
            return dict(self._breaks.get(gen, {}))

    async def post_reform(self, instr: dict):
        async with self._cond:
            self._reform = dict(instr)
            self._cond.notify_all()
        return True

    async def reform_status(self, gen: int):
        async with self._cond:
            return dict(self._done.get(gen, {}))


def create_elastic_coordinator():
    """Driver side: spawn a fresh named elastic coordinator for one
    gang incarnation.  Returns (name, handle)."""
    name = _ELASTIC_PREFIX + os.urandom(4).hex()
    coord = ray_tpu.remote(_ElasticCoordinator).options(
        name=name, num_cpus=0).remote()
    return name, coord


def kill_elastic_coordinator(name: str | None):
    if not name:
        return
    try:
        ray_tpu.kill(ray_tpu.get_actor(name))
    except Exception:
        pass


# ---------------------------------------------------------------- worker


def start_agent(worker):
    """Daemon thread per worker: long-polls the elastic coordinator so
    a loop thread blocked in ``session.report`` (not in a collective
    op — the death watch can't reach it there) still learns about a
    recovery and unwinds into the rejoin path."""
    sess = worker._session
    coord_name = worker._elastic_coord

    def _watch():
        while not sess.stop_requested and sess is worker._session:
            try:
                coord = ray_tpu.get_actor(coord_name)
            except Exception:
                return  # gang incarnation over
            try:
                g = ray_tpu.get(  # noqa: RTL001
                    coord.wait_signal.remote(sess.elastic_gen),
                    timeout=30)
            except ray_tpu.exceptions.GetTimeoutError:
                continue
            except Exception:
                if sess.stop_requested or sess is not worker._session:
                    return
                time.sleep(0.5)
                continue
            if g > sess.elastic_gen:
                sess.reform_pending_gen = g
                sess.continue_event.set()
                # Wait until the loop thread consumed the signal (its
                # generation advanced) before long-polling again.
                while (sess.elastic_gen < g and not sess.stop_requested
                       and sess is worker._session):
                    time.sleep(0.2)

    t = threading.Thread(target=_watch, daemon=True,
                         name="rt-elastic-agent")
    t.start()
    return t


def rejoin(worker, error, joining: bool = False) -> None:
    """Worker side of one re-formation, run on the LOOP thread (the
    one that was executing train_fn).  Raises on abort/deadline — the
    worker records the error and the driver cold-restarts."""
    sess = worker._session
    deadline = (cfg.train_reform_timeout_s + cfg.train_reform_jitter_s
                + 15.0)
    coord = ray_tpu.get_actor(worker._elastic_coord)

    if not joining:
        # 1. Tear down the local member of the broken group.  This
        # also aborts any in-flight bucket handles: the member's op
        # executor shuts down and pending waits fail with the group's
        # CollectiveGroupError.
        from ray_tpu.util import collective as col
        old_group = os.environ.get("RT_TRAIN_COLLECTIVE_GROUP") or None
        if old_group is not None:
            col.destroy_local_member(old_group)
        # 2. Drop reports the driver will never consume (it discards
        # the interrupted round; every rank re-reports from the
        # authoritative step after the re-shard).
        while True:
            try:
                sess.result_queue.get_nowait()
            except queue.Empty:
                break
        sess.result_queue.put((FLUSH, sess.elastic_gen + 1))
        st = sess._elastic_state
        info = {"step": (st or {}).get("step", -1),
                "has_state": st is not None,
                "iteration": sess.iteration}
        ray_tpu.get(coord.report_break.remote(
            sess.elastic_gen, worker.world_rank, info), timeout=60)

    # 3. Wait for the driver's instructions.
    instr = ray_tpu.get(coord.wait_reform.remote(sess.elastic_gen),
                        timeout=deadline)
    if instr.get("action") == "abort":
        raise error if error is not None else ElasticReset(
            "elastic reform aborted: " + str(instr.get("reason", "")))

    if not joining and worker.world_rank in instr.get("retired", ()):
        # Broker/driver shrink retired this rank: leave the training
        # loop cleanly.  StopIteration is caught by the _run() wrapper
        # as an orderly exit (result_queue gets its terminal None), the
        # driver reaps the actor and releases the bundle — no failure
        # budget consumed, no error recorded.
        raise StopIteration(
            f"rank {worker.world_rank} retired by elastic shrink "
            f"(generation {instr['gen']})")
    if joining:
        token = os.environ.get("RT_TRAIN_ELASTIC_TOKEN", "")
        new_rank = instr["joiners"][token]
    else:
        new_rank = instr["ranks"][str(worker.world_rank)]
    world = instr["world_size"]
    group = instr["group"]
    gen = instr["gen"]
    old_rank = worker.world_rank

    try:
        from ray_tpu.util import collective as col
        col.init_collective_group(world, new_rank, group_name=group)

        # Chaos hook: kill/err a member between group formation and
        # state adoption — the canonical mid-re-shard death.  The new
        # group's death watch (armed by the driver before post_reform)
        # aborts every survivor's sync, and the driver falls back to
        # the checkpoint.
        if failpoints.ACTIVE:
            act = failpoints.check("train.reform", peer=f"r{old_rank}")
            if act is not None:
                if act.kind == "kill":
                    os._exit(int(act.arg or 1))
                if act.kind == "error":
                    raise CollectiveGroupError(
                        group, "failpoint: injected re-shard fault at "
                        f"rank {old_rank}")
                if act.kind == "delay":
                    time.sleep(act.delay_s)

        auth_meta = _state_sync(group, sess)

        # Re-split datasets across the new world size and align epoch
        # counters to the authoritative rank so every member derives
        # the same per-epoch shuffle order.
        epochs = (auth_meta or {}).get("epochs") or {}
        worker._reshard_datasets(world, new_rank, epochs)

        # 4. Adopt the new identity (env + session + actor fields).
        os.environ["RT_TRAIN_WORLD_SIZE"] = str(world)
        os.environ["RT_TRAIN_WORLD_RANK"] = str(new_rank)
        os.environ["RT_TRAIN_LOCAL_RANK"] = str(new_rank)
        os.environ["RT_TRAIN_COLLECTIVE_GROUP"] = group
        worker.world_rank = new_rank
        worker.world_size = world
        worker.local_rank = new_rank
        sess.world_rank = new_rank
        sess.world_size = world
        sess.local_rank = new_rank
        if auth_meta is not None:
            sess.iteration = int(auth_meta.get("iteration", 0))
        sess.elastic_gen = gen
        sess.elastic_resizes += 1
        ray_tpu.get(coord.report_reform_done.remote(
            gen, new_rank, True, None), timeout=60)
        logger.info("elastic rejoin: rank %s -> %s/%s (gen %s)",
                    old_rank, new_rank, world, gen)
    except BaseException as e:
        try:
            ray_tpu.get(coord.report_reform_done.remote(
                gen, new_rank, False, repr(e)), timeout=10)
        except Exception:
            pass
        raise


def _state_sync(group_name: str, sess):
    """One fixed op sequence on the NEW group, every member: gather
    stash metadata, pick the authoritative holder (min committed step,
    lowest rank tiebreak), broadcast its pickled stash, adopt
    atomically.  Returns the authoritative meta (or None when no rank
    stashed state — the loop re-enters from the last checkpoint)."""
    from ray_tpu.util import collective as col
    g = col.get_group_handle(group_name)
    st = sess._elastic_state
    meta = {"step": (st or {}).get("step", -1),
            "has_state": st is not None,
            "iteration": sess.iteration,
            "epochs": {n: int(getattr(s, "epoch", 0))
                       for n, s in sess.dataset_shards.items()}}
    metas = g.collect("gather", meta)  # rank order
    holders = [(m["step"], r) for r, m in enumerate(metas)
               if m["has_state"]]
    if not holders:
        sess._elastic_state = None
        return None
    _auth_step, auth = min(holders)
    blob = pickle.dumps(st, protocol=pickle.HIGHEST_PROTOCOL) \
        if g.rank == auth else b""
    hdr = g.collect(f"src:{auth}", {"nbytes": len(blob)})
    n = int(hdr["nbytes"])
    if g.rank == auth:
        buf = np.frombuffer(bytearray(blob), dtype=np.uint8)
    else:
        buf = np.empty(n, dtype=np.uint8)
    if n:
        col.broadcast(buf, src_rank=auth, group_name=group_name)
    if g.rank != auth:
        state = pickle.loads(buf.tobytes())
    else:
        state = st
    # Atomic adoption: the fully-deserialized dict swaps in with one
    # reference assignment — there is no window where a reader can see
    # half of the old state and half of the new.
    sess._elastic_state = state
    return metas[auth]
