"""JaxConfig/JaxBackend: the TPU-native replacement for the reference's
torch NCCL process-group setup (train/torch/config.py:54
_setup_torch_process_group).

Instead of NCCL rendezvous, the gang wires the jax coordination service:
rank 0 publishes coordinator host:port, every rank calls
jax.distributed.initialize(coordinator, num_processes, process_id); XLA
then runs collectives over ICI within a slice and DCN across hosts.  Each
worker builds the gang's device Mesh from ScalingConfig's parallelism
axes; the user loop reads it via session.get_mesh().
"""

from __future__ import annotations

import dataclasses

import ray_tpu
from ray_tpu.train.backend import Backend, BackendConfig


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _init_jax_distributed(coordinator: str, num_processes: int,
                          process_id: int):
    import jax

    from ray_tpu._private.jax_utils import enable_cpu_collectives
    enable_cpu_collectives()
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def _coordinator_host() -> str:
    import socket
    return socket.gethostbyname(socket.gethostname())


@dataclasses.dataclass
class JaxConfig(BackendConfig):
    """use_distributed: wire jax.distributed across the gang (multi-host
    pods).  With one worker (single host owning the whole slice/chip) the
    coordination service is unnecessary and skipped."""
    use_distributed: bool = True
    virtual_cpu_devices: int = 0  # >0: force a virtual CPU mesh (tests)

    @property
    def backend_cls(self):
        return JaxBackend


class JaxBackend(Backend):
    def __init__(self):
        self._scaling_config = None
        self._config = None

    def on_start(self, worker_group, backend_config: JaxConfig):
        self._config = backend_config
        # JaxTrainer.training_loop stashes the ScalingConfig here so the
        # per-worker mesh builder knows the parallelism axes.
        self._scaling_config = getattr(backend_config, "_scaling_config",
                                       None)
        n = worker_group.num_workers
        if backend_config.use_distributed and n > 1:
            host = worker_group.execute_single(0, _coordinator_host)
            port = worker_group.execute_single(0, _free_port)
            coordinator = f"{host}:{port}"
            refs = [
                w.execute.remote(_init_jax_distributed, coordinator, n, i)
                for i, w in enumerate(worker_group.workers)
            ]
            ray_tpu.get(refs, timeout=300)

    def mesh_builder(self):
        """Returns a callable run ON each worker to build the gang mesh."""
        sc = self._scaling_config
        cfg = self._config
        virtual = cfg.virtual_cpu_devices if cfg else 0

        def _build():
            from ray_tpu._private.jax_utils import cpu_mesh_devices
            from ray_tpu.parallel.mesh import make_mesh
            import jax
            if virtual:
                devices = cpu_mesh_devices(virtual)
            else:
                devices = jax.devices()
            if sc is None:
                return None
            spec = sc.mesh_spec(len(devices))
            return make_mesh(spec, devices=devices)

        return _build
