from ray_tpu.train.jax.config import JaxConfig  # noqa: F401
from ray_tpu.train.jax.jax_trainer import JaxTrainer  # noqa: F401
from ray_tpu.train.jax.train_loop_utils import (  # noqa: F401
    prepare_mesh, prepare_batch_sharding,
)
