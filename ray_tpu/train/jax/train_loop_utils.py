"""In-loop helpers for JaxTrainer user code (reference analogue:
train/torch/train_loop_utils.py:49 prepare_model DDP-wrap — here the
equivalents hand out the mesh and shard data/state onto it)."""

from __future__ import annotations

from ray_tpu.air import session


def prepare_mesh():
    """The gang's jax Mesh (built by JaxBackend from ScalingConfig)."""
    mesh = session.get_mesh()
    if mesh is None:
        raise RuntimeError(
            "no mesh in this session — run inside JaxTrainer")
    return mesh


def prepare_batch_sharding(mesh, *axes):
    """NamedSharding for input batches: batch dim over (dp, fsdp)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    if not axes:
        axes = (("dp", "fsdp"),)
    return NamedSharding(mesh, P(*axes))
