"""JaxTrainer: the flagship TPU trainer (reference analogue:
train/torch/torch_trainer.py:15 TorchTrainer — here the framework below is
jax/pjit over a TPU mesh instead of torch DDP over NCCL)."""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.jax.config import JaxConfig


class JaxTrainer(DataParallelTrainer):
    _backend_config_cls = JaxConfig

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[Dict] = None,
                 jax_config: Optional[JaxConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[Dict] = None,
                 dataset_config: Optional[Dict] = None,
                 preprocessor=None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend_config=jax_config or JaxConfig(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            dataset_config=dataset_config,
            preprocessor=preprocessor,
            resume_from_checkpoint=resume_from_checkpoint)

    def training_loop(self) -> None:
        # Hand the backend the scaling config through the config object so
        # every worker can build the gang mesh (mesh axes live in
        # ScalingConfig — SURVEY §2.4).
        self._backend_config._scaling_config = self.scaling_config
        super().training_loop()
