"""BaseTrainer: fit() rides on Tune for execution.

Reference: python/ray/train/base_trainer.py:328 — `fit` wraps the trainer
into a Tune trainable (as_trainable :354-382) and runs a single-trial
Tuner, so checkpointing/fault-tolerance/experiment-dirs are shared with
tuning sweeps.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result


class TrainingFailedError(RuntimeError):
    pass


class BaseTrainer:
    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 **kwargs):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    def training_loop(self) -> None:
        """Subclass hook: runs INSIDE the trial; use session.report."""
        raise NotImplementedError

    def as_trainable(self):
        from ray_tpu.tune.execution.placement_groups import (
            PlacementGroupFactory)
        trainer = self

        def train_func(config: Dict):
            trainer.training_loop()

        train_func.__name__ = type(self).__name__
        # The trial actor is a lightweight supervisor; the worker gang gets
        # its own PG from BackendExecutor.start (2-phase gang reservation).
        train_func._pg_factory = PlacementGroupFactory([{"CPU": 0.1}])
        return train_func

    def fit(self) -> Result:
        from ray_tpu.tune.tuner import TuneConfig, Tuner
        tuner = Tuner(self.as_trainable(),
                      tune_config=TuneConfig(),
                      run_config=self.run_config)
        grid = tuner.fit()
        result = grid[0]
        if result.error is not None:
            raise TrainingFailedError(str(result.error)) from result.error
        return result
