"""GBDTTrainer: distributed gradient-boosted trees on the WorkerGroup
substrate.

Reference: python/ray/train/gbdt_trainer.py:70 (GBDTTrainer and its
xgboost/lightgbm subclasses) — there, distributed tree training rides
the same worker-gang substrate as the neural trainers, with xgboost's
rabit AllReduce as the collective.  Here the SAME shape is kept but the
booster is native: each rank holds a data shard, builds per-feature
gradient/hessian HISTOGRAMS locally, allreduces them through the
cluster's collective backend (util/collective ring — the rabit role),
and then every rank deterministically grows the identical tree from
the identical global histograms.  This is xgboost's ``hist`` algorithm
(Chen & Guestrin 2016 §3.3, approximate greedy with weighted quantile
bins) — the math any GBDT user expects, with no external dependency.

``XGBoostTrainer`` wraps the real xgboost library when it is
installed; in hermetic environments it raises ImportError pointing
here, keeping the native path the honest default.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

_EPS = 1e-12


# --------------------------------------------------------------- booster
def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _grad_hess(objective: str, pred: np.ndarray, y: np.ndarray):
    if objective == "binary:logistic":
        p = _sigmoid(pred)
        return p - y, np.maximum(p * (1.0 - p), 1e-6)
    # reg:squarederror
    return pred - y, np.ones_like(pred)


def _eval_metric(objective: str, pred: np.ndarray, y: np.ndarray):
    if objective == "binary:logistic":
        p = np.clip(_sigmoid(pred), 1e-7, 1 - 1e-7)
        return "logloss", float(
            -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))
    return "rmse", float(np.sqrt(np.mean((pred - y) ** 2)))


class _Tree:
    """Flat array-encoded binary tree grown depth-wise from GLOBAL
    histograms — every rank runs this identically, so no tree
    broadcast is needed (determinism IS the synchronization)."""

    __slots__ = ("feature", "threshold_bin", "left", "right", "value")

    def __init__(self):
        self.feature: list = []
        self.threshold_bin: list = []
        self.left: list = []
        self.right: list = []
        self.value: list = []

    def add_node(self):
        for a in (self.feature, self.threshold_bin, self.left,
                  self.right):
            a.append(-1)
        self.value.append(0.0)
        return len(self.value) - 1

    def predict_bins(self, binned: np.ndarray) -> np.ndarray:
        """binned: [n, features] uint8 bin indices -> leaf values."""
        out = np.zeros(len(binned), np.float64)
        node = np.zeros(len(binned), np.int64)
        feature = np.asarray(self.feature)
        thr = np.asarray(self.threshold_bin)
        left = np.asarray(self.left)
        right = np.asarray(self.right)
        value = np.asarray(self.value)
        live = feature[node] >= 0
        while live.any():
            f = feature[node[live]]
            go_left = binned[live, f] <= thr[node[live]]
            nxt = np.where(go_left, left[node[live]],
                           right[node[live]])
            node[live] = nxt
            live = feature[node] >= 0
        out = value[node]
        return out

    def to_dict(self):
        return {k: list(getattr(self, k)) for k in self.__slots__}

    @classmethod
    def from_dict(cls, d):
        t = cls()
        for k in cls.__slots__:
            setattr(t, k, list(d[k]))
        return t


def _grow_tree(binned, grad, hess, params, allreduce):
    """One boosting round.  ``allreduce(np.ndarray) -> np.ndarray``
    sums across ranks; everything else is rank-local."""
    n, n_feat = binned.shape
    n_bins = params["num_bins"]
    lam = params["reg_lambda"]
    gamma = params["gamma"]
    min_child = params["min_child_weight"]
    tree = _Tree()
    root = tree.add_node()
    node_of_row = np.zeros(n, np.int64)
    frontier = [root]
    for _depth in range(params["max_depth"]):
        if not frontier:
            break
        k = len(frontier)
        node_index = {nid: i for i, nid in enumerate(frontier)}
        # Local histograms for every frontier node at once:
        # [k, n_feat, n_bins] for G and H.
        gh = np.zeros((2, k, n_feat, n_bins), np.float64)
        on_frontier = np.isin(node_of_row, frontier)
        rows = np.nonzero(on_frontier)[0]
        if len(rows):
            ni = np.vectorize(node_index.get)(node_of_row[rows])
            for f in range(n_feat):
                b = binned[rows, f]
                np.add.at(gh[0, :, f, :], (ni, b), grad[rows])
                np.add.at(gh[1, :, f, :], (ni, b), hess[rows])
        gh = allreduce(gh)  # the rabit moment: global statistics
        new_frontier = []
        for nid in frontier:
            i = node_index[nid]
            g_tot = gh[0, i].sum(axis=1)[0]
            h_tot = gh[1, i].sum(axis=1)[0]
            # Best split over (feature, bin) from prefix sums.
            gl = np.cumsum(gh[0, i], axis=1)
            hl = np.cumsum(gh[1, i], axis=1)
            gr = g_tot - gl
            hr = h_tot - hl
            ok = (hl >= min_child) & (hr >= min_child)
            gain = 0.5 * (gl ** 2 / (hl + lam) + gr ** 2 / (hr + lam)
                          - g_tot ** 2 / (h_tot + lam)) - gamma
            gain[~ok] = -np.inf
            best = np.unravel_index(np.argmax(gain), gain.shape)
            if not np.isfinite(gain[best]) or gain[best] <= 0:
                tree.value[nid] = float(
                    -g_tot / (h_tot + lam) * params["eta"])
                continue
            f, b = int(best[0]), int(best[1])
            lid, rid = tree.add_node(), tree.add_node()
            tree.feature[nid] = f
            tree.threshold_bin[nid] = b
            tree.left[nid] = lid
            tree.right[nid] = rid
            mine = node_of_row == nid
            go_left = mine & (binned[:, f] <= b)
            node_of_row[go_left] = lid
            node_of_row[mine & ~go_left] = rid
            new_frontier += [lid, rid]
        frontier = new_frontier
    # Any still-unset frontier leaves (depth limit hit): weight them.
    # One batched allreduce — the frontier is identical on every rank
    # (tree growth is deterministic from global histograms).
    if frontier:
        stats = np.array([[grad[node_of_row == nid].sum(),
                           hess[node_of_row == nid].sum()]
                          for nid in frontier])
        stats = allreduce(stats)
        for (g_leaf, h_leaf), nid in zip(stats, frontier):
            tree.value[nid] = float(
                -g_leaf / (h_leaf + lam) * params["eta"])
    return tree


DEFAULT_PARAMS = {
    "objective": "reg:squarederror",
    "eta": 0.3,
    "max_depth": 4,
    "num_boost_round": 20,
    "reg_lambda": 1.0,
    "gamma": 0.0,
    "min_child_weight": 1.0,
    "num_bins": 64,
}


def _gbdt_train_loop(config: Dict):
    """Runs ON each gang worker (the reference's _xgboost_train_fn
    role): shard -> bins -> boosting rounds with allreduced
    histograms -> per-round session.report + final model checkpoint."""
    import ray_tpu.util.collective as col
    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint

    params = dict(DEFAULT_PARAMS)
    params.update(config.get("params") or {})
    label_col = config["label_column"]
    rank = session.get_world_rank()
    world = session.get_world_size()

    df = session.get_dataset_shard("train").to_pandas()
    y = df[label_col].to_numpy(np.float64)
    x = df.drop(columns=[label_col]).to_numpy(np.float64)

    own_group = False
    if world > 1:
        # Ride the gang-wide group the BackendExecutor prepared (every
        # rank is already a member, death-watch armed); standalone use
        # outside a train gang self-organizes one.  Histograms are MiB
        # class, so sync rides the peer-to-peer collective fast plane.
        group = session.get_collective_group()
        if group is None:
            group = f"gbdt_{session.get_trial_id() or 'default'}"
            col.init_collective_group(world, rank, group_name=group)
            own_group = True

        def allreduce(arr):
            return col.allreduce(np.ascontiguousarray(arr),
                                 group_name=group)
    else:
        def allreduce(arr):
            return arr

    # Global-ish quantile bin edges: mean of per-rank percentiles
    # (deterministic everywhere after the allreduce; the reference's
    # approx quantile sketch plays this role).
    n_bins = params["num_bins"]
    qs = np.linspace(0, 100, n_bins + 1)[1:-1]
    local_edges = np.percentile(x, qs, axis=0) \
        if len(x) else np.zeros((len(qs), x.shape[1]))
    edges = allreduce(local_edges) / world
    binned = np.empty(x.shape, np.int64)
    for f in range(x.shape[1]):
        binned[:, f] = np.searchsorted(edges[:, f], x[:, f])

    trees = []
    pred = np.zeros(len(y), np.float64)
    ckpt = session.get_checkpoint()
    if ckpt is not None:
        state = ckpt.to_dict()
        trees = [_Tree.from_dict(d) for d in state["trees"]]
        edges = np.asarray(state["edges"])
        for f in range(x.shape[1]):
            binned[:, f] = np.searchsorted(edges[:, f], x[:, f])
        for t in trees:
            pred += t.predict_bins(binned)

    for rnd in range(len(trees), params["num_boost_round"]):
        grad, hess = _grad_hess(params["objective"], pred, y)
        tree = _grow_tree(binned, grad, hess, params, allreduce)
        trees.append(tree)
        pred += tree.predict_bins(binned)
        name, local_metric = _eval_metric(params["objective"], pred, y)
        stats = allreduce(np.array([local_metric * len(y),
                                    float(len(y))]))
        session.report(
            {f"train-{name}": stats[0] / max(stats[1], 1),
             "round": rnd},
            checkpoint=Checkpoint.from_dict({
                "trees": [t.to_dict() for t in trees],
                "edges": np.asarray(edges),
                "params": params,
                "label_column": label_col,
            }))
    if world > 1 and own_group:
        try:
            col.destroy_collective_group(group)
        except Exception:
            pass


class GBDTBoosterModel:
    """Inference-side model reconstructed from a Checkpoint."""

    def __init__(self, trees, edges, params):
        self.trees = trees
        self.edges = np.asarray(edges)
        self.params = params

    @classmethod
    def from_checkpoint(cls, checkpoint) -> "GBDTBoosterModel":
        d = checkpoint.to_dict()
        return cls([_Tree.from_dict(t) for t in d["trees"]],
                   d["edges"], d["params"])

    def predict(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        binned = np.empty(x.shape, np.int64)
        for f in range(x.shape[1]):
            binned[:, f] = np.searchsorted(self.edges[:, f], x[:, f])
        margin = np.zeros(len(x), np.float64)
        for t in self.trees:
            margin += t.predict_bins(binned)
        if self.params["objective"] == "binary:logistic":
            return _sigmoid(margin)
        return margin


class GBDTTrainer(DataParallelTrainer):
    """Distributed gradient-boosted trees (reference:
    train/gbdt_trainer.py:70).  Same call shape as the reference:

        GBDTTrainer(label_column="y",
                    params={"objective": "reg:squarederror", ...},
                    datasets={"train": ds},
                    scaling_config=ScalingConfig(num_workers=2))
    """

    def __init__(self, *, label_column: str,
                 params: Optional[Dict] = None,
                 train_loop_per_worker: Optional[Callable] = None,
                 **kwargs):
        super().__init__(
            train_loop_per_worker or _gbdt_train_loop,
            train_loop_config={"label_column": label_column,
                               "params": params or {}},
            **kwargs)


class XGBoostTrainer(GBDTTrainer):
    """The real-xgboost subclass (reference:
    train/xgboost/xgboost_trainer.py).  Requires the external xgboost
    package; hermetic environments use GBDTTrainer (same API, native
    hist booster)."""

    def __init__(self, **kwargs):
        try:
            import xgboost  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "XGBoostTrainer needs the external 'xgboost' package; "
                "use GBDTTrainer for the dependency-free native "
                "histogram booster (same distributed algorithm)") from e
        super().__init__(**kwargs)
