"""Streaming Data -> Train ingest (the L10 composition on our planes).

Reference: python/ray/train/_internal/dataset_iterator.py /
DataIterator — each rank consumes its dataset shard through the
streaming executor instead of a materialized snapshot, so the shard's
next window transforms/shuffles on the cluster WHILE the worker runs
its train step, and an epoch boundary no longer stalls the step loop:

* Within an epoch, ``iter_batches`` streams through the operator-graph
  executor (data/_internal/streaming_executor.py): map windows and the
  transfer-plane shuffle's reduces complete remotely while the consumer
  holds a batch.
* Across epochs, the NEXT epoch's pipeline is primed by a background
  thread as soon as the current epoch starts draining — by the time the
  step loop re-enters ``iter_batches``, the first window of the
  reshuffled epoch is already materializing.

Per-epoch shuffling derives its seed from ``(shuffle_seed, epoch)``
(deterministic: a fixed ``DatasetConfig.shuffle_seed`` reproduces the
exact batch sequence across runs and parallelism settings — see
``Dataset.random_shuffle``).  NOTE the documented semantics shift under
streaming ingest: ``global_shuffle`` becomes a per-epoch shuffle of the
rank's OWN shard (blocks are sharded once, rows reshuffle within the
shard every epoch) — the Ray-style local-shuffle tradeoff.  For a
one-shot whole-dataset shuffle across shards, set RT_DATA_STREAMING=0
or shuffle explicitly before passing the dataset to the trainer.
"""

from __future__ import annotations

import threading
from typing import Iterator, Optional

from ray_tpu._private import locksan


class StreamingDatasetShard:
    """One rank's streaming view of a prepared dataset.  Everything a
    plain Dataset offers still works (``count``/``take_all``/... are
    delegated); ``iter_batches`` adds the per-epoch reshuffle + the
    cross-epoch window priming."""

    def __init__(self, ds, *, shuffle_each_epoch: bool = False,
                 shuffle_seed: Optional[int] = None):
        self._ds = ds
        self._shuffle = bool(shuffle_each_epoch)
        if shuffle_seed is None:
            import random
            shuffle_seed = random.randrange(1 << 30)
        self._seed = shuffle_seed
        self._epoch = 0
        self._lock = locksan.make_lock("StreamingDatasetShard._lock")
        self._primed = None  # (epoch, kw_key, first_item_or_END, iter)
        self._prime_thread = None
        self._closed = False

    # ------------------------------------------------------------ delegate
    def __getattr__(self, name):
        return getattr(self._ds, name)

    @property
    def epoch(self) -> int:
        """Epochs started so far (== times iter_batches was entered)."""
        return self._epoch

    # ------------------------------------------------------------- epochs
    def _epoch_dataset(self, epoch: int):
        if not self._shuffle:
            return self._ds
        return self._ds.random_shuffle(seed=(self._seed * 2654435761
                                             + epoch) % (1 << 31))

    @staticmethod
    def _kw_key(kw: dict) -> tuple:
        return tuple(sorted(kw.items()))

    _END = object()

    def _prime(self, epoch: int, kw: dict):
        """Background-build the next epoch's iterator and pull its
        first batch, so the reshuffle's first window is already in
        flight when the step loop re-enters iter_batches."""
        if self._prime_thread is not None and self._prime_thread.is_alive():
            return

        def _run():
            try:
                it = self._epoch_dataset(epoch).iter_batches(**kw)
                first = next(it, self._END)
                with self._lock:
                    # A close() that outlived its join(timeout) must
                    # still win: publishing after it would leak the
                    # iterator's in-flight window forever.
                    if self._closed:
                        publish = False
                    else:
                        self._primed = (epoch, self._kw_key(kw),
                                        first, it)
                        publish = True
                if not publish:
                    close = getattr(it, "close", None)
                    if close is not None:
                        close()
            except Exception:
                with self._lock:
                    self._primed = None

        self._prime_thread = threading.Thread(
            target=_run, daemon=True, name="rt-ingest-prime")
        self._prime_thread.start()

    def _take_primed(self, epoch: int, kw: dict):
        if self._prime_thread is not None:
            self._prime_thread.join()
            self._prime_thread = None
        with self._lock:
            primed, self._primed = self._primed, None
        if primed is None or primed[0] != epoch \
                or primed[1] != self._kw_key(kw):
            if primed is not None:
                close = getattr(primed[3], "close", None)
                if close is not None:
                    close()
            return None
        _e, _k, first, it = primed
        if first is self._END:
            return iter(())

        def _chain():
            yield first
            yield from it
        return _chain()

    def iter_batches(self, _prime_next: bool = True, **kw) -> Iterator:
        # Eager body (not a generator): the epoch advances and the next
        # epoch's priming starts at CALL time, not at first consumption.
        epoch = self._epoch
        self._epoch += 1
        it = self._take_primed(epoch, kw)
        if it is None:
            it = self._epoch_dataset(epoch).iter_batches(**kw)
        if self._shuffle and _prime_next:
            self._prime(epoch + 1, kw)

        def _drain():
            try:
                yield from it
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()
        return _drain()

    def iter_epochs(self, epochs: int, **kw):
        """``epochs`` successive (re-shuffled) passes.  The final epoch
        skips the next-epoch prime: for a shuffled shard the prime runs
        the exchange's whole map phase, and an epoch nobody will
        consume must not pay it."""
        for e in range(epochs):
            yield self.iter_batches(_prime_next=e + 1 < epochs, **kw)

    # Tensor/row consumption MUST route through this wrapper's
    # iter_batches: the trainer skips the eager global shuffle under
    # streaming ingest, so delegating these to the raw Dataset (whose
    # identically-named methods call Dataset.iter_batches internally)
    # would silently train on UNSHUFFLED data.  Shuffle-invariant
    # surfaces (count/schema/sum/...) still delegate via __getattr__.
    def iter_rows(self, **kw) -> Iterator:
        for batch in self.iter_batches(batch_format="pylist", **kw):
            yield from batch

    def iter_torch_batches(self, *, batch_size: int = 256, **kw):
        import torch
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kw):
            yield {k: torch.as_tensor(v) for k, v in batch.items()} \
                if isinstance(batch, dict) else torch.as_tensor(batch)

    def iter_jax_batches(self, *, batch_size: int = 256, sharding=None,
                         **kw):
        import jax
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kw):
            if sharding is not None:
                place = lambda v: jax.device_put(v, sharding)  # noqa: E731
            else:
                place = jax.device_put
            yield ({k: place(v) for k, v in batch.items()}
                   if isinstance(batch, dict) else place(batch))

    def resplit(self, ds, *, epoch: Optional[int] = None):
        """Elastic re-shard: swap in this rank's NEW shard of the
        dataset (split across the re-formed gang's world size) without
        rebuilding the wrapper.  The primed next-epoch pipeline over
        the OLD shard is dropped — its rows belong to a partition that
        no longer exists.  ``epoch`` (the authoritative rank's counter)
        realigns this member so every rank keeps deriving the same
        per-epoch shuffle seed, and the next ``iter_batches`` pass
        partitions the whole dataset exactly once across the new gang:
        no row is dropped or double-read WITHIN an epoch started after
        the re-form.  Rows of the interrupted epoch are replayed
        exactly as far as the step rollback replays steps."""
        if self._prime_thread is not None:
            self._prime_thread.join(timeout=30)
            self._prime_thread = None
        with self._lock:
            primed, self._primed = self._primed, None
        if primed is not None:
            close = getattr(primed[3], "close", None)
            if close is not None:
                close()
        self._ds = ds
        if epoch is not None:
            self._epoch = int(epoch)

    def close(self):
        """Drop a primed-but-unconsumed epoch (cancels its window)."""
        with self._lock:
            self._closed = True
        if self._prime_thread is not None:
            self._prime_thread.join(timeout=30)
            self._prime_thread = None
        with self._lock:
            primed, self._primed = self._primed, None
        if primed is not None:
            close = getattr(primed[3], "close", None)
            if close is not None:
                close()
