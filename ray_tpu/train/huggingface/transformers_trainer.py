"""TransformersTrainer: run a HuggingFace Trainer per worker rank.

Reference: python/ray/train/huggingface/huggingface_trainer.py — the
user's trainer_init_per_worker builds a transformers.Trainer inside each
rank; the gang's torch process group (gloo here, TorchBackend) makes HF/
accelerate data-parallel; metrics from the HF log history reach the
Result through session.report.  On this framework the TPU fine-tuning
path is JaxTrainer + models/gpt; this trainer covers existing HF/torch
codebases on CPU workers.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ray_tpu.air import session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train.torch.torch_trainer import TorchConfig, TorchTrainer


class TransformersTrainer(TorchTrainer):
    def __init__(self, trainer_init_per_worker: Callable, *,
                 trainer_init_config: Optional[Dict] = None,
                 torch_config: Optional[TorchConfig] = None,
                 **kwargs):
        def train_loop(config: Dict):
            import os

            import torch.distributed as dist
            # transformers/accelerate discover the gang via env.
            if dist.is_initialized():
                os.environ.setdefault("RANK", str(dist.get_rank()))
                os.environ.setdefault("WORLD_SIZE",
                                      str(dist.get_world_size()))
                os.environ.setdefault("LOCAL_RANK",
                                      str(dist.get_rank()))
            hf_trainer = trainer_init_per_worker(config)
            # Weights-level resume on restarts (optimizer state is not
            # carried — documented divergence from HF's own
            # resume_from_checkpoint, which needs its internal
            # checkpoint-dir layout).
            restored = session.get_checkpoint()
            if restored is not None:
                state = restored.to_dict().get("model_state")
                if state:
                    hf_trainer.model.load_state_dict(state)
            result = hf_trainer.train()
            metrics = dict(result.metrics or {})
            for row in reversed(hf_trainer.state.log_history):
                if "loss" in row:
                    metrics.setdefault("loss", row["loss"])
                    break
            ckpt = None
            if session.get_world_rank() == 0:
                # state_dict into a dict checkpoint: round-trips through
                # Tune save/restore (directory checkpoints don't) and
                # leaves nothing on /tmp.
                ckpt = Checkpoint.from_dict({"model_state": {
                    k: v.detach().cpu()
                    for k, v in hf_trainer.model.state_dict().items()}})
            session.report(metrics, checkpoint=ckpt)

        super().__init__(train_loop,
                         train_loop_config=trainer_init_config or {},
                         torch_config=torch_config, **kwargs)
