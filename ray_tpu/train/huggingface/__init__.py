from ray_tpu.train.huggingface.transformers_trainer import (  # noqa: F401
    TransformersTrainer,
)
