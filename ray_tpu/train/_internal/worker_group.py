"""WorkerGroup: the gang of rank-labeled training actors.

Reference: python/ray/train/_internal/worker_group.py (WorkerGroup over
actor handles; execute/execute_single).  Workers live in one placement
group so the gang is scheduled atomically (reference: backend_executor
start inside the Tune trial's PG).

Elastic mode (train/elastic.py): the user loop runs inside a rejoin
wrapper — a CollectiveGroupError on the gang's group (member death
aborts it via the death watch) or an ElasticReset (resize grant /
report-blocked unwind) drops into the re-formation protocol instead of
killing the worker, and the loop re-enters at the re-sharded state.
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Any, Callable, List, Optional

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu.air import session as air_session
from ray_tpu.util.collective import CollectiveMixin
from ray_tpu.util.collective.types import CollectiveGroupError

logger = logging.getLogger(__name__)


class _TrainWorker(CollectiveMixin):
    """Actor hosting one rank of the gang.  CollectiveMixin lets the
    BackendExecutor wire the gang into a host collective group at start
    (data-parallel gradient / histogram sync on the transfer plane)."""

    def __init__(self, world_rank: int, world_size: int, local_rank: int):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self._session: Optional[air_session._Session] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._env: dict = {}
        self._dataset_entries: Optional[dict] = None
        self._elastic_coord: Optional[str] = None

    # generic remote execution --------------------------------------------
    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def set_env(self, env: dict):
        import os
        self._env.update(env)
        os.environ.update({k: str(v) for k, v in env.items()})
        return True

    def node_info(self) -> dict:
        import os
        return {"hostname": socket.gethostname(),
                "rank": self.world_rank,
                "pid": os.getpid()}

    # dataset sharding -----------------------------------------------------
    def _shard_one(self, entry, world: int, rank: int):
        """Deterministic whole-block split: every rank computes the
        same split and keeps its own shard (reference:
        data_parallel_trainer dataset sharding to workers).
        DatasetConfig(split=False) datasets arrive whole on every rank
        (the trainer sends (ds, split?, ingest_opts) triples; bare
        datasets / 2-tuples from older callers default to split, no
        streaming ingest opts)."""
        ingest = None
        if isinstance(entry, tuple):
            ds, do_split = entry[0], entry[1]
            if len(entry) > 2:
                ingest = entry[2]
        else:
            ds, do_split = entry, True
        if do_split and world > 1:
            shard = ds.split(world)[rank]
        else:
            shard = ds
        return shard, ingest

    def _shard_datasets(self, world: int, rank: int):
        for name, entry in (self._dataset_entries or {}).items():
            shard, ingest = self._shard_one(entry, world, rank)
            if ingest:
                # Streaming ingest: per-epoch reshuffle through the
                # streaming executor, next epoch primed while the
                # step loop drains the current one.
                from ray_tpu.train.ingest import StreamingDatasetShard
                shard = StreamingDatasetShard(
                    shard,
                    shuffle_each_epoch=ingest.get(
                        "shuffle_each_epoch", False),
                    shuffle_seed=ingest.get("shuffle_seed"))
            self._session.dataset_shards[name] = shard

    def _reshard_datasets(self, world: int, rank: int, epochs: dict):
        """Elastic resize: re-split every dataset across the NEW world
        size.  Streaming shards swap their underlying dataset in place
        (the primed next-epoch pipeline over the OLD shard is closed)
        and align their epoch counter to the authoritative rank, so
        every member keeps deriving the same per-epoch shuffle and the
        next epoch partitions the whole dataset exactly once across
        the re-formed gang."""
        for name, entry in (self._dataset_entries or {}).items():
            shard, ingest = self._shard_one(entry, world, rank)
            cur = self._session.dataset_shards.get(name)
            if cur is not None and hasattr(cur, "resplit"):
                cur.resplit(shard, epoch=epochs.get(name))
            elif ingest:
                from ray_tpu.train.ingest import StreamingDatasetShard
                s = StreamingDatasetShard(
                    shard,
                    shuffle_each_epoch=ingest.get(
                        "shuffle_each_epoch", False),
                    shuffle_seed=ingest.get("shuffle_seed"))
                ep = epochs.get(name)
                if ep is not None:
                    s._epoch = int(ep)
                self._session.dataset_shards[name] = s
            else:
                self._session.dataset_shards[name] = shard

    # training loop --------------------------------------------------------
    def start_training(self, train_fn: Callable, config: dict,
                       checkpoint=None, trial_name: str = "",
                       trial_id: str = "", mesh_builder: Callable = None,
                       elastic_join: bool = False):
        import os
        from ray_tpu.train import elastic
        mesh = mesh_builder() if mesh_builder is not None else None
        self._session = air_session._Session(
            world_rank=self.world_rank, world_size=self.world_size,
            local_rank=self.local_rank, trial_name=trial_name,
            trial_id=trial_id, mesh=mesh, checkpoint=checkpoint)
        fps = (config or {}).pop("__failpoints__", None)
        if fps:
            # Chaos wiring: arm failpoints in THIS worker process
            # (train.step / train.reform sites and below).
            from ray_tpu._private import failpoints
            failpoints.configure(fps)
        self._dataset_entries = (config or {}).pop("__datasets__", None)
        self._elastic_coord = (os.environ.get("RT_TRAIN_ELASTIC_COORD")
                               or None)
        if elastic_join:
            # A joiner's rank/world/shards are assigned by the reform
            # instructions; its session starts at the driver's current
            # generation so it long-polls the right reform.
            self._session.elastic_gen = int(
                os.environ.get("RT_TRAIN_ELASTIC_GEN", "0"))
        else:
            self._shard_datasets(self.world_size, self.world_rank)
        self._error = None
        if self._elastic_coord:
            elastic.start_agent(self)

        def _call():
            train_fn(config) if config is not None else train_fn()

        def _run():
            air_session._set_session(self._session)
            try:
                if elastic_join:
                    elastic.rejoin(self, None, joining=True)
                while True:
                    try:
                        _call()
                        break
                    except StopIteration:
                        break
                    except (elastic.ElasticReset,
                            CollectiveGroupError) as e:
                        if self._elastic_coord is None:
                            raise
                        # getattr: an error re-raised at get() may have
                        # been wrapped without the cause's attributes;
                        # only a POSITIVELY different group is a user
                        # error — unknown means assume the gang broke.
                        broken = getattr(e, "group", None)
                        if isinstance(e, CollectiveGroupError) \
                                and broken is not None \
                                and broken != os.environ.get(
                                    "RT_TRAIN_COLLECTIVE_GROUP"):
                            # A user-managed group broke, not the gang:
                            # that is a user error, not a resize.
                            raise
                        # Re-form in place; rejoin raises on
                        # abort/deadline and the driver cold-restarts.
                        elastic.rejoin(self, e)
            except StopIteration:
                pass
            except BaseException as e:
                logger.warning("train loop exited with %r", e)
                self._error = e
            finally:
                self._session.result_queue.put(None)

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        return True

    def next_result(self):
        """Block until the user loop reports (or finishes).  Returns
        (metrics, checkpoint), an elastic flush marker, or None when
        the loop ended."""
        from ray_tpu.train import elastic
        item = self._session.result_queue.get()
        if item is None:
            if self._error is not None:
                raise self._error
            return None
        if isinstance(item, tuple) and item and item[0] == elastic.FLUSH:
            # Stale-round flush (see elastic.FLUSH): not a user report,
            # so the loop is NOT unblocked here.
            return item
        self._session.continue_event.set()
        metrics, ckpt = item
        return (metrics, ckpt)

    def shutdown_training(self):
        if self._session is not None:
            self._session.stop_requested = True
            self._session.continue_event.set()
            # Drop any primed-but-unconsumed next-epoch pipeline (its
            # in-flight window and block refs would otherwise linger
            # until process exit).
            for shard in self._session.dataset_shards.values():
                close = getattr(shard, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass
        if self._thread is not None:
            self._thread.join(timeout=cfg.train_worker_join_s)
        return True


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: dict,
                 placement_group=None):
        self.num_workers = num_workers
        self.workers: List[Any] = []
        self._resources = dict(resources_per_worker)
        self._pg = placement_group
        # Which PG bundle each live worker occupies (parallel to
        # ``workers``); ``capacity`` bounds elastic scale-up — a freed
        # bundle (dead member) can host a joiner, but the PG cannot
        # grow.
        self.bundle_indices: List[int] = []
        self.capacity = num_workers
        for rank in range(num_workers):
            self.workers.append(self._spawn(rank, rank, num_workers))
            self.bundle_indices.append(rank)

    def _spawn(self, rank: int, bundle_index: int, world: int):
        cls = ray_tpu.remote(_TrainWorker)
        opts = dict(
            num_cpus=self._resources.get("CPU", 0),
            resources={k: v for k, v in self._resources.items()
                       if k != "CPU"})
        if self._pg is not None:
            opts["placement_group"] = self._pg
            opts["placement_group_bundle_index"] = bundle_index
        return cls.options(**opts).remote(rank, world, rank)

    def apply_reform(self, workers: List[Any], bundles: List[int]):
        """Adopt the post-reform live set (survivors in new-rank order,
        joiners appended); dead members' handles drop out here."""
        self.workers = list(workers)
        self.bundle_indices = list(bundles)
        self.num_workers = len(self.workers)

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(
            [w.execute.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=cfg.train_start_timeout_s)

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(
            self.workers[rank].execute.remote(fn, *args, **kwargs),
            timeout=cfg.train_start_timeout_s)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        self.bundle_indices = []
