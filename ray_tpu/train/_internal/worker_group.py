"""WorkerGroup: the gang of rank-labeled training actors.

Reference: python/ray/train/_internal/worker_group.py (WorkerGroup over
actor handles; execute/execute_single).  Workers live in one placement
group so the gang is scheduled atomically (reference: backend_executor
start inside the Tune trial's PG).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Callable, List, Optional

import ray_tpu
from ray_tpu.air import session as air_session
from ray_tpu.util.collective import CollectiveMixin


class _TrainWorker(CollectiveMixin):
    """Actor hosting one rank of the gang.  CollectiveMixin lets the
    BackendExecutor wire the gang into a host collective group at start
    (data-parallel gradient / histogram sync on the transfer plane)."""

    def __init__(self, world_rank: int, world_size: int, local_rank: int):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self._session: Optional[air_session._Session] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._env: dict = {}

    # generic remote execution --------------------------------------------
    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def set_env(self, env: dict):
        import os
        self._env.update(env)
        os.environ.update({k: str(v) for k, v in env.items()})
        return True

    def node_info(self) -> dict:
        return {"hostname": socket.gethostname(),
                "rank": self.world_rank}

    # training loop --------------------------------------------------------
    def start_training(self, train_fn: Callable, config: dict,
                       checkpoint=None, trial_name: str = "",
                       trial_id: str = "", mesh_builder: Callable = None):
        mesh = mesh_builder() if mesh_builder is not None else None
        self._session = air_session._Session(
            world_rank=self.world_rank, world_size=self.world_size,
            local_rank=self.local_rank, trial_name=trial_name,
            trial_id=trial_id, mesh=mesh, checkpoint=checkpoint)
        datasets = (config or {}).pop("__datasets__", None)
        if datasets:
            # Deterministic whole-block split: every rank computes the
            # same split and keeps its own shard (reference:
            # data_parallel_trainer dataset sharding to workers).
            # DatasetConfig(split=False) datasets arrive whole on every
            # rank (the trainer sends (ds, split?, ingest_opts)
            # triples; bare datasets / 2-tuples from older callers
            # default to split, no streaming ingest opts).
            for name, entry in datasets.items():
                ingest = None
                if isinstance(entry, tuple):
                    ds, do_split = entry[0], entry[1]
                    if len(entry) > 2:
                        ingest = entry[2]
                else:
                    ds, do_split = entry, True
                if do_split and self.world_size > 1:
                    shards = ds.split(self.world_size)
                    shard = shards[self.world_rank]
                else:
                    shard = ds
                if ingest:
                    # Streaming ingest: per-epoch reshuffle through the
                    # streaming executor, next epoch primed while the
                    # step loop drains the current one.
                    from ray_tpu.train.ingest import StreamingDatasetShard
                    shard = StreamingDatasetShard(
                        shard,
                        shuffle_each_epoch=ingest.get(
                            "shuffle_each_epoch", False),
                        shuffle_seed=ingest.get("shuffle_seed"))
                self._session.dataset_shards[name] = shard
        self._error = None

        def _run():
            air_session._set_session(self._session)
            try:
                train_fn(config) if config is not None else train_fn()
            except StopIteration:
                pass
            except BaseException as e:
                self._error = e
            finally:
                self._session.result_queue.put(None)

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        return True

    def next_result(self):
        """Block until the user loop reports (or finishes).  Returns
        (metrics, checkpoint) or None when the loop ended."""
        item = self._session.result_queue.get()
        if item is None:
            if self._error is not None:
                raise self._error
            return None
        self._session.continue_event.set()
        metrics, ckpt = item
        return (metrics, ckpt)

    def shutdown_training(self):
        if self._session is not None:
            self._session.stop_requested = True
            self._session.continue_event.set()
            # Drop any primed-but-unconsumed next-epoch pipeline (its
            # in-flight window and block refs would otherwise linger
            # until process exit).
            for shard in self._session.dataset_shards.values():
                close = getattr(shard, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        return True


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: dict,
                 placement_group=None):
        self.num_workers = num_workers
        self.workers: List[Any] = []
        cls = ray_tpu.remote(_TrainWorker)
        for rank in range(num_workers):
            opts = dict(
                num_cpus=resources_per_worker.get("CPU", 0),
                resources={k: v for k, v in resources_per_worker.items()
                           if k != "CPU"})
            if placement_group is not None:
                opts["placement_group"] = placement_group
                opts["placement_group_bundle_index"] = rank
            self.workers.append(
                cls.options(**opts).remote(rank, num_workers, rank))

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ray_tpu.get(
            [w.execute.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=600)

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs):
        return ray_tpu.get(
            self.workers[rank].execute.remote(fn, *args, **kwargs),
            timeout=600)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
