"""BackendExecutor: drives the WorkerGroup through a training run.

Reference: python/ray/train/_internal/backend_executor.py:42 (start :92,
start_training :274) — create the gang, run Backend setup hooks, launch
the user loop everywhere, then stream per-round results back.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train._internal.worker_group import WorkerGroup


class TrainingResult:
    def __init__(self, metrics: dict, checkpoint: Optional[Checkpoint]):
        self.metrics = metrics
        self.checkpoint = checkpoint


class TrainingFailedError(RuntimeError):
    pass


class TrainingWorkerError(TrainingFailedError):
    """A gang worker died from a SYSTEM fault (actor/node death), not a
    user-code exception — the gang can be restarted from the last
    checkpoint (reference: backend_executor.py:274 catching
    RayActorError into TrainingWorkerError for the retry loop)."""


def _is_worker_death(e: BaseException) -> bool:
    from ray_tpu._private import protocol
    from ray_tpu import exceptions as rexc
    from ray_tpu.util.collective.types import CollectiveGroupError
    if isinstance(e, CollectiveGroupError):
        # A surviving rank's collective op failed because the GANG
        # broke (member death aborts the group) — restartable, exactly
        # like observing the dead actor directly.  Checked before the
        # TaskError clause: remote errors multi-inherit both.
        return True
    if isinstance(e, rexc.TaskError):
        # A USER exception re-raised from the train loop (remote errors
        # multi-inherit TaskError + the original type) — even if the
        # original type is e.g. ConnectionError, restarts won't help.
        return False
    return isinstance(e, (rexc.ActorDiedError, rexc.ActorUnavailableError,
                          rexc.WorkerCrashedError, rexc.ObjectLostError,
                          protocol.ConnectionLost, ConnectionError))


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig):
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()
        self.scaling_config = scaling_config
        self.worker_group: Optional[WorkerGroup] = None
        self._pg = None
        self._collective_group: Optional[str] = None

    _placement_group = None

    def start(self, placement_group=None):
        """Idempotent: a retried start after a partial failure reuses the
        placement group and replaces any partially-created gang."""
        sc = self.scaling_config
        if self._placement_group is None:
            if placement_group is None:
                pgf = sc.as_placement_group_factory()
                self._pg = pgf.create()
                ok = ray_tpu.wait_placement_group_ready(self._pg,
                                                        timeout=120)
                if not ok:
                    raise TrainingFailedError(
                        "train worker gang PG not ready")
                placement_group = self._pg
            self._placement_group = placement_group
        if self.worker_group is not None:
            self.worker_group.shutdown()
            self.worker_group = None
        self._start_workers()

    def _start_workers(self):
        import os
        sc = self.scaling_config
        self._destroy_collective_group()
        self.worker_group = WorkerGroup(
            sc.num_workers, sc._resources, self._placement_group)
        # A gang-wide host collective group for data-parallel gradient
        # / histogram sync (util.collective on the transfer plane).
        # Named per incarnation so a gang restart gets a fresh
        # coordinator instead of colliding with the dead one's name.
        group = None
        if sc.num_workers > 1:
            group = f"train_dp_{os.urandom(4).hex()}"
        try:
            # Rank/world env everywhere (reference: rank env wiring in
            # backend_executor._setup_gang).  All workers in flight at
            # once; a per-worker get() would serialize N round trips.
            env = {
                "RT_TRAIN_WORLD_SIZE": sc.num_workers,
            }
            if group is not None:
                env["RT_TRAIN_COLLECTIVE_GROUP"] = group
            ray_tpu.get(
                [w.set_env.remote(dict(env, RT_TRAIN_WORLD_RANK=rank,
                                       RT_TRAIN_LOCAL_RANK=rank))
                 for rank, w in enumerate(self.worker_group.workers)],
                timeout=120)
            if group is not None:
                from ray_tpu.util import collective as col
                col.create_collective_group(
                    self.worker_group.workers, sc.num_workers,
                    list(range(sc.num_workers)), group_name=group)
                self._collective_group = group
            self.backend.on_start(self.worker_group, self.backend_config)
        except Exception as e:
            if _is_worker_death(e):
                raise TrainingWorkerError(str(e)) from e
            raise

    def _destroy_collective_group(self):
        if self._collective_group is None:
            return
        try:
            from ray_tpu.util import collective as col
            col.destroy_collective_group(self._collective_group)
        except Exception:
            pass
        self._collective_group = None

    def restart(self):
        """Gang-level fault recovery: tear the (partially dead) gang down
        and start a fresh one in the same placement group.  The backend's
        on_start runs again on the new incarnation, so the jax
        coordination service re-initializes with a fresh coordinator
        (SURVEY hard-part #4: collective rendezvous lifecycle tied to
        actor restarts).  Reference: backend_executor start/shutdown
        around worker failures."""
        if self.worker_group is not None:
            self.worker_group.shutdown()
            self.worker_group = None
        self._start_workers()

    def start_training(self, train_fn: Callable, config: dict,
                       checkpoint: Optional[Checkpoint] = None,
                       trial_name: str = "", trial_id: str = ""):
        self.backend.on_training_start(self.worker_group,
                                       self.backend_config)
        mesh_builder = getattr(self.backend, "mesh_builder", lambda: None)()
        refs = [
            w.start_training.remote(
                train_fn, config, checkpoint, trial_name, trial_id,
                mesh_builder)
            for w in self.worker_group.workers
        ]
        try:
            ray_tpu.get(refs, timeout=600)
        except Exception as e:
            if _is_worker_death(e):
                raise TrainingWorkerError(str(e)) from e
            raise

    def get_next_results(self) -> Optional[List[TrainingResult]]:
        """One report round from every rank; None when the loop finished.
        All ranks must report the same number of times (reference enforces
        the same invariant)."""
        refs = [w.next_result.remote() for w in self.worker_group.workers]
        try:
            raw = ray_tpu.get(refs, timeout=3600)
        except Exception as e:
            if _is_worker_death(e):
                raise TrainingWorkerError(str(e)) from e
            raise TrainingFailedError(str(e)) from e
        finished = [r is None for r in raw]
        if all(finished):
            return None
        if any(finished):
            raise TrainingFailedError(
                "ranks reported unevenly (some finished, some reported)")
        return [TrainingResult(m, c) for (m, c) in raw]

    def finish_training(self):
        if self.worker_group is not None:
            # Submit every shutdown first so they overlap; then drain
            # one by one to keep the per-worker exception isolation
            # (submission itself can raise during driver teardown).
            refs = []
            for w in self.worker_group.workers:
                try:
                    refs.append(w.shutdown_training.remote())
                except Exception:
                    pass
            for ref in refs:
                try:
                    ray_tpu.get(ref, timeout=30)
                except Exception:
                    pass

    def shutdown(self):
        try:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
        except Exception:
            pass
        self._destroy_collective_group()
        if self.worker_group is not None:
            self.worker_group.shutdown()
            self.worker_group = None
        if self._pg is not None:
            try:
                from ray_tpu.util.placement_group import (
                    remove_placement_group)
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
