"""BackendExecutor: drives the WorkerGroup through a training run.

Reference: python/ray/train/_internal/backend_executor.py:42 (start :92,
start_training :274) — create the gang, run Backend setup hooks, launch
the user loop everywhere, then stream per-round results back.

Elastic mode (ScalingConfig.elastic): a member death observed here (or
a resize request) triggers an IN-PLACE re-formation through
train/elastic.py — survivors rendezvous a fresh collective group at
the new world size, re-shard in-memory state over the collective data
plane, and the result pump resumes against the re-formed gang.  A cold
gang restart (``restart``) remains the fallback when survivors drop
below quorum or the re-shard itself fails; only cold restarts consume
FailureConfig.max_failures.
"""

from __future__ import annotations

import logging
import os
import random
import time
from typing import Callable, List, Optional, Tuple

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train._internal.worker_group import WorkerGroup
from ray_tpu.util.metrics import Counter

logger = logging.getLogger(__name__)

# Elastic in-place recoveries vs cold gang restarts: distinct budgets,
# distinct counters (satellite: FailureConfig.max_failures counts only
# the cold path).
ELASTIC_RESIZES = Counter(
    "train_elastic_resizes_total",
    "Successful in-place elastic gang re-formations (member death "
    "absorbed or resize grant applied without a trial restart)")
GANG_RESTARTS = Counter(
    "train_gang_restarts_total",
    "Cold gang restarts from the last checkpoint (worker death without "
    "elastic mode, quorum loss, or a failed re-shard)")


class TrainingResult:
    def __init__(self, metrics: dict, checkpoint: Optional[Checkpoint]):
        self.metrics = metrics
        self.checkpoint = checkpoint


class TrainingFailedError(RuntimeError):
    pass


class TrainingWorkerError(TrainingFailedError):
    """A gang worker died from a SYSTEM fault (actor/node death), not a
    user-code exception — the gang can be restarted from the last
    checkpoint (reference: backend_executor.py:274 catching
    RayActorError into TrainingWorkerError for the retry loop)."""


class _ResizeRequested(Exception):
    """Internal: an elastic resize grant interrupted the result pump."""


def _is_worker_death(e: BaseException) -> bool:
    from ray_tpu._private import protocol
    from ray_tpu import exceptions as rexc
    from ray_tpu.util.collective.types import CollectiveGroupError
    if isinstance(e, CollectiveGroupError):
        # A surviving rank's collective op failed because the GANG
        # broke (member death aborts the group) — restartable, exactly
        # like observing the dead actor directly.  Checked before the
        # TaskError clause: remote errors multi-inherit both.
        return True
    if isinstance(e, rexc.TaskError):
        # A USER exception re-raised from the train loop (remote errors
        # multi-inherit TaskError + the original type) — even if the
        # original type is e.g. ConnectionError, restarts won't help.
        return False
    return isinstance(e, (rexc.ActorDiedError, rexc.ActorUnavailableError,
                          rexc.WorkerCrashedError, rexc.ObjectLostError,
                          protocol.ConnectionLost, ConnectionError))


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig):
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()
        self.scaling_config = scaling_config
        self.worker_group: Optional[WorkerGroup] = None
        self._pg = None
        self._collective_group: Optional[str] = None
        self._elastic = bool(getattr(scaling_config, "elastic", False)) \
            and scaling_config.num_workers > 1
        self._elastic_coord = None
        self._elastic_coord_name: Optional[str] = None
        self._gen = 0
        # Per-worker in-flight next_result refs: elasticity needs the
        # pump to know exactly which refs are outstanding so a
        # recovery can discard the interrupted round (a re-issued ref
        # would double-consume a survivor's report queue).
        self._pending: Optional[List[Tuple[object, object]]] = None
        self._joiners: List[Tuple[str, object, int]] = []
        self._resize_target: Optional[int] = None
        self._train_args: Optional[tuple] = None
        # PG bundle indices handed back to the cluster by an elastic
        # shrink; a later grow re-reserves them (two-phase, via GCS)
        # before spawning joiners into them.
        self._released_bundles: set = set()
        # Cluster-autopilot registration (one gang == one broker
        # workload): a daemon agent reports size/demand every
        # autopilot_report_period_s and applies broker-initiated
        # resize grants through request_elastic_resize — the same
        # entry point the driver and `rt resize` use.
        gname = getattr(scaling_config, "name", None) \
            or f"gang-{os.urandom(3).hex()}"
        self._gang_name = gname
        self._autopilot_wid = f"train:{gname}"
        self._autopilot_thread = None
        self._autopilot_stop = None
        # True while the broker (not a member death) shrank us: only
        # then does a restored grant auto-grow the gang back — a death
        # never triggers a surprise self-heal grow.
        self._broker_shrunk = False
        # An explicit operator directive (rt resize) pins the reported
        # demand at its target; otherwise the grow-back logic would
        # treat the broker's still-full grant as a signal to undo the
        # operator's shrink on the very next report.
        self._want_override: Optional[int] = None

    _placement_group = None

    def start(self, placement_group=None):
        """Idempotent: a retried start after a partial failure reuses the
        placement group and replaces any partially-created gang."""
        sc = self.scaling_config
        if self._placement_group is None:
            if placement_group is None:
                pgf = sc.as_placement_group_factory()
                self._pg = pgf.create()
                ok = ray_tpu.wait_placement_group_ready(self._pg,
                                                        timeout=120)
                if not ok:
                    raise TrainingFailedError(
                        "train worker gang PG not ready")
                placement_group = self._pg
            self._placement_group = placement_group
        if self.worker_group is not None:
            self.worker_group.shutdown()
            self.worker_group = None
        self._start_workers()

    # ------------------------------------------------------ gcs helpers
    def _pg_id(self):
        return getattr(self._placement_group, "id", None)

    @staticmethod
    def _gcs(method: str, body: dict):
        from ray_tpu._private.worker import global_worker
        return global_worker.gcs_call(method, body)

    def _start_workers(self):
        from ray_tpu.train import elastic as _elastic
        sc = self.scaling_config
        if self._released_bundles and self._pg_id() is not None:
            # Cold restart after a shrink: the full-size gang respawns
            # into bundles 0..N-1, so released ones must be re-reserved
            # first (best effort — a failed reacquire surfaces as the
            # restart's own placement failure).
            try:
                self._gcs("reacquire_bundles", {
                    "pg_id": self._pg_id(),
                    "indices": sorted(self._released_bundles)})
            except Exception:
                pass
            self._released_bundles.clear()
        self._destroy_collective_group()
        _elastic.kill_elastic_coordinator(self._elastic_coord_name)
        self._elastic_coord = self._elastic_coord_name = None
        self._gen = 0
        self._pending = None
        self._joiners = []
        self._resize_target = None
        self._want_override = None
        self.worker_group = WorkerGroup(
            sc.num_workers, sc._resources, self._placement_group)
        # A gang-wide host collective group for data-parallel gradient
        # / histogram sync (util.collective on the transfer plane).
        # Named per incarnation so a gang restart gets a fresh
        # coordinator instead of colliding with the dead one's name.
        group = None
        if sc.num_workers > 1:
            group = f"train_dp_{os.urandom(4).hex()}"
        try:
            # Rank/world env everywhere (reference: rank env wiring in
            # backend_executor._setup_gang).  All workers in flight at
            # once; a per-worker get() would serialize N round trips.
            env = {
                "RT_TRAIN_WORLD_SIZE": sc.num_workers,
            }
            if group is not None:
                env["RT_TRAIN_COLLECTIVE_GROUP"] = group
            if self._elastic:
                name, coord = _elastic.create_elastic_coordinator()
                self._elastic_coord_name, self._elastic_coord = \
                    name, coord
                env["RT_TRAIN_ELASTIC_COORD"] = name
            ray_tpu.get(
                [w.set_env.remote(dict(env, RT_TRAIN_WORLD_RANK=rank,
                                       RT_TRAIN_LOCAL_RANK=rank))
                 for rank, w in enumerate(self.worker_group.workers)],
                timeout=120)
            if group is not None:
                from ray_tpu.util import collective as col
                col.create_collective_group(
                    self.worker_group.workers, sc.num_workers,
                    list(range(sc.num_workers)), group_name=group)
                self._collective_group = group
            self.backend.on_start(self.worker_group, self.backend_config)
        except Exception as e:
            if _is_worker_death(e):
                raise TrainingWorkerError(str(e)) from e
            raise

    def _destroy_collective_group(self):
        if self._collective_group is None:
            return
        try:
            from ray_tpu.util import collective as col
            col.destroy_collective_group(self._collective_group)
        except Exception:
            pass
        self._collective_group = None

    def restart(self):
        """Gang-level COLD fault recovery: tear the (partially dead)
        gang down and start a fresh one in the same placement group.
        The backend's on_start runs again on the new incarnation, so
        the jax coordination service re-initializes with a fresh
        coordinator (SURVEY hard-part #4: collective rendezvous
        lifecycle tied to actor restarts).  Reference: backend_executor
        start/shutdown around worker failures.  This is the path that
        consumes FailureConfig.max_failures; elastic re-forms do not
        pass through here."""
        GANG_RESTARTS.inc()
        if self.worker_group is not None:
            self.worker_group.shutdown()
            self.worker_group = None
        self._start_workers()

    def start_training(self, train_fn: Callable, config: dict,
                       checkpoint: Optional[Checkpoint] = None,
                       trial_name: str = "", trial_id: str = ""):
        self.backend.on_training_start(self.worker_group,
                                       self.backend_config)
        mesh_builder = getattr(self.backend, "mesh_builder", lambda: None)()
        # Joiners spawned by an elastic resize re-run the same entry
        # point (their rank/shards come from the reform instructions).
        self._train_args = (train_fn, config, checkpoint, trial_name,
                            trial_id, mesh_builder)
        refs = [
            w.start_training.remote(
                train_fn, config, checkpoint, trial_name, trial_id,
                mesh_builder)
            for w in self.worker_group.workers
        ]
        try:
            ray_tpu.get(refs, timeout=cfg.train_start_timeout_s)
        except Exception as e:
            if _is_worker_death(e):
                raise TrainingWorkerError(str(e)) from e
            raise
        self._start_autopilot_agent()

    # ------------------------------------------------- autopilot agent
    def _autopilot_decl(self, live: int) -> dict:
        sc = self.scaling_config
        return {"kind": "train",
                "priority": int(getattr(sc, "priority", 50)),
                "min_units": self._quorum() if self._elastic else live,
                "max_units": (self.worker_group.capacity
                              if self.worker_group is not None
                              else sc.num_workers),
                "elastic": self._elastic}

    def _start_autopilot_agent(self):
        import threading
        if self._autopilot_thread is not None:
            return
        self._autopilot_stop = threading.Event()
        self._autopilot_thread = threading.Thread(
            target=self._autopilot_agent_loop, daemon=True,
            name=f"rt-gang-agent-{self._gang_name}")
        self._autopilot_thread.start()

    def _autopilot_agent_loop(self):
        """Report the gang to the GCS broker and apply its resize
        grants.  Trains always *want* their full declared size back, so
        a grant moving away from the live size is the broker speaking:
        below live = reclaim (shrink through the re-form path), back
        above live = the spike drained (grow, but ONLY when the broker
        itself did the shrinking — a member death never triggers a
        surprise self-heal grow from here).  Explicit `rt resize`
        directives ride the same reply and always apply."""
        stop = self._autopilot_stop
        while not stop.wait(cfg.autopilot_report_period_s):
            try:
                wg = self.worker_group
                if wg is None or not wg.workers:
                    continue
                live = len(wg.workers)
                want = (self._want_override
                        if self._want_override is not None
                        else wg.capacity)
                reply = self._gcs("arbiter_report", {
                    "wid": self._autopilot_wid,
                    "want": want, "units_now": live,
                    "decl": self._autopilot_decl(live)})
                if not isinstance(reply, dict) or not reply.get("ok"):
                    continue
                target = reply.get("directive")
                from_directive = target is not None
                if target is None and self._elastic:
                    granted = int(reply.get("granted", live))
                    if granted < live:
                        target = granted
                    elif granted > live and self._broker_shrunk:
                        target = min(granted, wg.capacity)
                if target is None:
                    continue
                target = int(target)
                if (not self._elastic or target == live
                        or self._train_args is None
                        or self._resize_target is not None
                        or target < self._quorum()
                        or target > wg.capacity):
                    continue
                self.request_elastic_resize(target)
                if from_directive:
                    self._want_override = (target
                                           if target < wg.capacity
                                           else None)
                else:
                    # Still below full declared size => the broker owns
                    # the deficit and a later grant may grow us further.
                    # (`target < live` would clear the flag on a PARTIAL
                    # grow — e.g. 2 -> 3 of 4 while serve releases nodes
                    # one cooldown at a time — stranding the gang below
                    # capacity with no one willing to grow it.)
                    self._broker_shrunk = target < wg.capacity
            except Exception:
                logger.debug("autopilot gang agent iteration failed",
                             exc_info=True)

    def _stop_autopilot_agent(self):
        if self._autopilot_stop is not None:
            self._autopilot_stop.set()
        if self._autopilot_thread is not None:
            self._autopilot_thread.join(timeout=2.0)
            self._autopilot_thread = None
        try:
            self._gcs("arbiter_unregister", {"wid": self._autopilot_wid})
        except Exception:
            pass

    # ------------------------------------------------------- result pump
    def _get_refs(self, refs, deadline):
        """Blocking get.  Elastic mode waits in short slices so a
        resize request (posted from another thread) interrupts the
        pump instead of riding out the full round deadline."""
        if not self._elastic:
            return ray_tpu.get(refs, timeout=cfg.train_result_timeout_s)
        while True:
            if self._resize_target is not None:
                raise _ResizeRequested()
            remain = deadline - time.monotonic()
            if remain <= 0:
                raise ray_tpu.exceptions.GetTimeoutError(
                    "train report round timed out")
            try:
                return ray_tpu.get(refs, timeout=min(1.0, remain))
            except ray_tpu.exceptions.GetTimeoutError:
                continue

    @staticmethod
    def _is_flush(item) -> bool:
        from ray_tpu.train import elastic
        return (isinstance(item, (tuple, list)) and len(item) == 2
                and item[0] == elastic.FLUSH)

    def _acquire_round(self):
        """One full round of next_result values, with post-reform flush
        markers (elastic.FLUSH) skipped: a marker slot re-polls that
        worker alone, so the real reports stay aligned across ranks."""
        deadline = time.monotonic() + cfg.train_result_timeout_s
        raw = list(self._get_refs([r for _, r in self._pending],
                                  deadline))
        i = 0
        while i < len(raw):
            if self._is_flush(raw[i]):
                w, _ = self._pending[i]
                nref = w.next_result.remote()
                self._pending[i] = (w, nref)
                raw[i] = self._get_refs([nref], deadline)[0]
            else:
                i += 1
        return raw

    def get_next_results(self) -> Optional[List[TrainingResult]]:
        """One report round from every rank; None when the loop finished.
        All ranks must report the same number of times (reference enforces
        the same invariant)."""
        while True:
            if self._pending is None:
                self._pending = [(w, w.next_result.remote())
                                 for w in self.worker_group.workers]
            try:
                raw = self._acquire_round()
            except _ResizeRequested:
                self._elastic_recover(None)
                continue
            except Exception as e:
                if self._elastic and _is_worker_death(e):
                    # In-place re-formation: survivors rendezvous the
                    # new world size; the interrupted round is
                    # discarded (every rank re-reports from the
                    # authoritative step after the re-shard).  Raises
                    # TrainingWorkerError itself when the re-form
                    # can't complete (quorum, deadline, re-shard
                    # failure) — the cold-restart path.
                    self._elastic_recover(e)
                    continue
                if _is_worker_death(e):
                    raise TrainingWorkerError(str(e)) from e
                raise TrainingFailedError(str(e)) from e
            self._pending = None
            finished = [r is None for r in raw]
            if all(finished):
                return None
            if any(finished):
                raise TrainingFailedError(
                    "ranks reported unevenly (some finished, some "
                    "reported)")
            return [TrainingResult(m, c) for (m, c) in raw]

    # --------------------------------------------------- elastic re-form
    def request_elastic_resize(self, target_world_size: int):
        """Resize the gang to ``target_world_size`` in place.  The
        driver, `rt resize <gang> <n>`, and the autopilot broker all
        land here.

        Grow: spawn joiners into free placement-group bundles
        (re-reserving any a previous shrink released), then break the
        current incarnation so survivors and joiners rendezvous the new
        world size together; joiners receive the authoritative state
        over the collective plane like any recovering member.

        Shrink: mark the target and break the incarnation — the re-form
        path retires the highest ranks (clean StopIteration exit, no
        failure budget consumed), kills their actors, and releases
        their bundles so the freed nodes really return to the cluster.
        Thread-safe against a pump blocked in get_next_results."""
        if not self._elastic:
            raise RuntimeError("elastic resize requires "
                               "ScalingConfig(elastic=True)")
        wg = self.worker_group
        if wg is None or self._train_args is None:
            raise RuntimeError("no running gang to resize")
        live = len(wg.workers)
        target_world_size = int(target_world_size)
        if target_world_size == live:
            raise ValueError(f"gang is already at world size {live}")
        if target_world_size < live:
            if target_world_size < self._quorum():
                raise ValueError(
                    f"target world size {target_world_size} is below "
                    f"the elastic quorum floor {self._quorum()}")
            self._resize_target = target_world_size
            if self._collective_group is not None:
                from ray_tpu.util import collective as col
                col.abort_collective_group(self._collective_group,
                                           "elastic shrink")
            return
        free = [i for i in range(wg.capacity)
                if i not in wg.bundle_indices]
        need = target_world_size - live
        if need > len(free):
            raise ValueError(
                f"resize to {target_world_size} needs {need} bundles "
                f"but only {len(free)} are free (gang capacity "
                f"{wg.capacity})")
        reacquire = [i for i in free[:need]
                     if i in self._released_bundles]
        if reacquire and self._pg_id() is not None:
            try:
                r = self._gcs("reacquire_bundles", {
                    "pg_id": self._pg_id(), "indices": reacquire})
            except Exception as e:
                raise ValueError(
                    f"cannot grow to {target_world_size}: bundle "
                    f"re-reservation RPC failed ({e})") from e
            got = set(r.get("reacquired", ())) if isinstance(r, dict) \
                else set()
            self._released_bundles -= got
            missing = [i for i in reacquire if i not in got]
            if missing:
                raise ValueError(
                    f"cannot grow to {target_world_size}: released "
                    f"bundles {missing} could not be re-reserved "
                    f"(capacity taken by another workload; retry on a "
                    f"later grant)")
        (train_fn, config, checkpoint, trial_name, trial_id,
         mesh_builder) = self._train_args
        # The joiner handshake must stay bounded well below the
        # broker's stale-report window: this path runs on the autopilot
        # agent thread, and a wedged joiner that blocks it past the
        # window gets the gang's registration GC'd out from under a
        # live gang (its budget returns to the pool and data soaks the
        # slots).  On any failure kill everything spawned this attempt
        # so the next grant retries from a clean slate.
        spawned = []
        try:
            for k in range(need):
                w = wg._spawn(live + k, free[k], target_world_size)
                spawned.append(("j" + os.urandom(3).hex(), w, free[k]))
                env = {"RT_TRAIN_ELASTIC_COORD":
                       self._elastic_coord_name,
                       "RT_TRAIN_ELASTIC_TOKEN": spawned[-1][0],
                       "RT_TRAIN_ELASTIC_GEN": self._gen,
                       "RT_TRAIN_WORLD_SIZE": target_world_size,
                       "RT_TRAIN_WORLD_RANK": live + k,
                       "RT_TRAIN_LOCAL_RANK": live + k}
                ray_tpu.get(w.set_env.remote(env),  # noqa: RTL001
                            timeout=10)
                ray_tpu.get(  # noqa: RTL001
                    w.start_training.remote(train_fn, config,
                                            checkpoint, trial_name,
                                            trial_id, mesh_builder,
                                            True),
                    timeout=min(10.0, cfg.train_start_timeout_s))
        except Exception as e:
            for (_, w, _) in spawned:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            raise ValueError(
                f"cannot grow to {target_world_size}: joiner "
                f"handshake failed ({e}); retry on a later "
                f"grant") from e
        self._joiners.extend(spawned)
        self._resize_target = target_world_size
        # Break the running incarnation: every survivor's next
        # collective op (or parked report, via the worker agents) drops
        # into the rejoin path.
        if self._collective_group is not None:
            from ray_tpu.util import collective as col
            col.abort_collective_group(self._collective_group,
                                       "elastic resize")

    def _quorum(self) -> int:
        sc = self.scaling_config
        q = getattr(sc, "elastic_min_workers", None)
        if q is None:
            q = cfg.train_elastic_min_workers
        return max(1, int(q))

    def _reform_fail(self, msg: str, err):
        # Release workers parked in wait_reform before falling back.
        try:
            ray_tpu.get(self._elastic_coord.post_reform.remote(
                {"gen": self._gen + 1, "action": "abort",
                 "reason": msg}), timeout=10)
        except Exception:
            pass
        logger.warning("elastic re-form failed (%s); falling back to "
                       "cold checkpoint restart", msg)
        e = TrainingWorkerError(f"elastic re-form failed: {msg}")
        if err is not None:
            raise e from err
        raise e

    def _elastic_recover(self, err):
        """Driver side of one re-formation (train/elastic.py protocol).
        On success the pump continues against the re-formed gang; on
        quorum loss / deadline / re-shard failure raises
        TrainingWorkerError so the trainer's cold-restart loop takes
        over."""
        from ray_tpu.util import collective as col
        wg = self.worker_group
        old_workers = list(wg.workers)
        old_bundles = list(wg.bundle_indices)
        old_world = len(old_workers)
        gen = self._gen
        coord = self._elastic_coord
        timeout = cfg.train_reform_timeout_s
        deadline = time.monotonic() + timeout + random.uniform(
            0.0, max(0.0, cfg.train_reform_jitter_s))
        self._pending = None  # discard the interrupted round
        logger.warning(
            "train gang broke (%s); attempting elastic re-form "
            "(generation %s)", err, gen + 1)

        # Make sure every survivor breaks: abort the old group
        # (idempotent when the death watch already killed it) and
        # announce the recovery so worker agents unwind report-blocked
        # loops.
        if self._collective_group is not None:
            col.abort_collective_group(
                self._collective_group,
                "elastic re-form" if err is None else str(err))
        try:
            ray_tpu.get(coord.begin_recovery.remote(gen + 1), timeout=30)
        except Exception as e:
            self._reform_fail(f"elastic coordinator unreachable: {e}",
                              err)

        # Collect survivor breaks under the bounded deadline; a settle
        # window separates "everyone who can report has" from "one
        # straggler is still unwinding".
        settle = min(2.0, timeout / 5.0)
        last: dict = {}
        stable_since = time.monotonic()
        while True:
            now = time.monotonic()
            if now >= deadline:
                break
            try:
                b = ray_tpu.get(coord.breaks.remote(gen),  # noqa: RTL001
                                timeout=30)
            except Exception as e:
                self._reform_fail(f"break collection failed: {e}", err)
            if b != last:
                last, stable_since = b, now
            elif last and now - stable_since >= settle:
                break
            time.sleep(0.2)
        survivors = sorted(int(r) for r in last)
        joiners = list(self._joiners)
        if len(survivors) < self._quorum():
            self._reform_fail(
                f"{len(survivors)} survivors of {old_world} < quorum "
                f"{self._quorum()}", err)
        # Broker/driver shrink: retire the HIGHEST old ranks down to
        # the requested size (clamped to quorum — a resize directive
        # can never push the gang below its floor, even racing a
        # member death that already shrank the survivor set).
        retired: List[int] = []
        resize = self._resize_target
        if resize is not None:
            want = max(int(resize), self._quorum())
            if len(survivors) + len(joiners) > want:
                keep = max(want - len(joiners), 0)
                retired = survivors[keep:]
                survivors = survivors[:keep]
        new_world = len(survivors) + len(joiners)

        # Compact new ranks: survivors in old-rank order, then joiners.
        group = f"train_dp_{os.urandom(4).hex()}"
        gcoord = col.ensure_coordinator(group, new_world)
        ranks: dict = {}
        joiner_ranks: dict = {}
        mapping: dict = {}
        new_workers, new_bundles = [], []
        for new_rank, old_rank in enumerate(survivors):
            w = old_workers[old_rank]
            ranks[str(old_rank)] = new_rank
            new_workers.append(w)
            new_bundles.append(old_bundles[old_rank])
            aid = getattr(w, "_actor_id", None)
            if aid is not None:
                mapping[aid.hex()] = new_rank
        for k, (token, w, bidx) in enumerate(joiners):
            rank = len(survivors) + k
            joiner_ranks[token] = rank
            new_workers.append(w)
            new_bundles.append(bidx)
            aid = getattr(w, "_actor_id", None)
            if aid is not None:
                mapping[aid.hex()] = rank
        # Death watch BEFORE members register: a member dying
        # mid-re-shard aborts the new group fast (clean fallback, never
        # a torn state).
        try:
            ray_tpu.get(gcoord.watch.remote(mapping), timeout=60)
        except Exception:
            logger.warning("could not arm death watch for re-formed "
                           "group '%s'", group, exc_info=True)
        instr = {"gen": gen + 1, "group": group,
                 "world_size": new_world, "ranks": ranks,
                 "joiners": joiner_ranks,
                 "retired": retired,
                 "dead_ranks": [r for r in range(old_world)
                                if r not in survivors
                                and r not in retired],
                 "old_world": old_world}
        try:
            ray_tpu.get(coord.post_reform.remote(instr), timeout=30)
        except Exception as e:
            self._reform_fail(f"posting reform failed: {e}", err)

        # Await every member's re-shard ack under its own window.
        done_deadline = time.monotonic() + timeout
        detail = "re-shard deadline expired"
        ok = False
        while time.monotonic() < done_deadline:
            try:
                st = ray_tpu.get(  # noqa: RTL001
                    coord.reform_status.remote(gen + 1), timeout=30)
            except Exception as e:
                detail = f"reform status poll failed: {e}"
                break
            bad = [f"rank {r}: {v[1]}" for r, v in st.items()
                   if not v[0]]
            if bad:
                detail = "; ".join(bad)
                break
            if len(st) == new_world:
                ok = True
                break
            time.sleep(0.2)
        if not ok:
            col.abort_collective_group(group, "re-form failed")
            self._reform_fail(detail, err)

        old_group, self._collective_group = \
            self._collective_group, group
        if old_group is not None:
            # Reap the broken incarnation's coordinator actor (members
            # already dropped their local halves during rejoin).
            try:
                col.destroy_collective_group(old_group)
            except Exception:
                pass
        wg.apply_reform(new_workers, new_bundles)
        self._joiners = []
        self._resize_target = None
        self._gen = gen + 1
        ELASTIC_RESIZES.inc()
        if retired:
            # Retired members exited their loops cleanly
            # (StopIteration in rejoin); reap the actors and hand
            # their bundles back so the freed CPU leaves the gang's
            # reservation and returns to the cluster pool.
            rel = []
            for old_rank in retired:
                try:
                    ray_tpu.kill(old_workers[old_rank])
                except Exception:
                    pass
                rel.append(old_bundles[old_rank])
            if self._pg_id() is not None:
                try:
                    r = self._gcs("release_bundles", {
                        "pg_id": self._pg_id(), "indices": rel})
                    if isinstance(r, dict):
                        self._released_bundles.update(
                            r.get("released", ()))
                except Exception:
                    logger.warning("bundle release after elastic "
                                   "shrink failed", exc_info=True)
        logger.warning(
            "elastic re-form complete: world %s -> %s (generation %s, "
            "dead ranks %s, %s joiners, %s retired)", old_world,
            new_world, gen + 1, instr["dead_ranks"],
            len(joiner_ranks), len(retired))

    def finish_training(self):
        if self.worker_group is not None:
            # Submit every shutdown first so they overlap; then drain
            # one by one to keep the per-worker exception isolation
            # (submission itself can raise during driver teardown).
            refs = []
            for w in self.worker_group.workers:
                try:
                    refs.append(w.shutdown_training.remote())
                except Exception:
                    pass
            for ref in refs:
                try:
                    ray_tpu.get(ref, timeout=30)
                except Exception:
                    pass

    def shutdown(self):
        from ray_tpu.train import elastic as _elastic
        self._stop_autopilot_agent()
        try:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
        except Exception:
            pass
        self._destroy_collective_group()
        _elastic.kill_elastic_coordinator(self._elastic_coord_name)
        self._elastic_coord = self._elastic_coord_name = None
        if self.worker_group is not None:
            self.worker_group.shutdown()
            self.worker_group = None
        if self._pg is not None:
            try:
                from ray_tpu.util.placement_group import (
                    remove_placement_group)
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
