from ray_tpu.train.sklearn.sklearn_trainer import SklearnTrainer  # noqa: F401
