"""SklearnTrainer: fit a scikit-learn estimator as a supervised trial.

Reference: python/ray/train/sklearn/sklearn_trainer.py — the estimator
fits inside a worker (CPU-parallel via joblib n_jobs), metrics and the
fitted model come back as a Result + Checkpoint.  Rides BaseTrainer ->
Tune like every other trainer, so retries/experiment dirs are shared.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, Optional

from ray_tpu.air import session
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.base_trainer import BaseTrainer

MODEL_KEY = "estimator"


class SklearnTrainer(BaseTrainer):
    def __init__(self, *, estimator, datasets: Dict,
                 label_column: Optional[str] = None,
                 params: Optional[Dict] = None,
                 scoring: Optional[Dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config)
        self._estimator = estimator
        self._datasets = datasets
        self._label_column = label_column
        self._params = params or {}
        self._scoring = scoring or {}

    def _xy(self, ds):
        df = ds.to_pandas() if hasattr(ds, "to_pandas") else ds
        if self._label_column is None:
            return df, None
        return (df.drop(columns=[self._label_column]),
                df[self._label_column])

    def training_loop(self) -> None:
        est = self._estimator
        if self._params:
            est = est.set_params(**self._params)
        x, y = self._xy(self._datasets["train"])
        est.fit(x, y)
        metrics: Dict[str, Any] = {}
        for name, ds in self._datasets.items():
            if name == "train":
                continue
            vx, vy = self._xy(ds)
            metrics[f"{name}_score"] = float(est.score(vx, vy))
        if self._scoring:
            vx, vy = self._xy(self._datasets.get("valid",
                                                 self._datasets["train"]))
            for name, fn in self._scoring.items():
                metrics[name] = float(fn(est, vx, vy))
        if "train_score" not in metrics:
            metrics["train_score"] = float(est.score(x, y))
        session.report(metrics, checkpoint=Checkpoint.from_dict(
            {MODEL_KEY: pickle.dumps(est)}))

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        return pickle.loads(checkpoint.to_dict()[MODEL_KEY])
