"""Workflow: durable execution of task DAGs.

Reference: python/ray/workflow — workflow.run/run_async (api.py:120,166),
per-task checkpointing in task_executor.py:50 (each task's output is
persisted before dependents run), WorkflowManagementActor
(workflow_access.py:88) tracking status, storage/ for the persistence
layer.  Scoped re-design: the DAG IR is ray_tpu.dag; every node's result
is checkpointed to the workflow's storage directory under a deterministic
task key, so `resume` replays only the tasks whose checkpoints are
missing (exactly-once-ish per task).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode

_DEFAULT_STORAGE = None

STATUS_RUNNING = "RUNNING"
STATUS_SUCCESSFUL = "SUCCESSFUL"
STATUS_FAILED = "FAILED"
STATUS_RESUMABLE = "RESUMABLE"


def init(storage: Optional[str] = None):
    """Set the storage root (reference: workflow.init)."""
    global _DEFAULT_STORAGE
    _DEFAULT_STORAGE = storage


def _storage_root() -> str:
    global _DEFAULT_STORAGE
    if _DEFAULT_STORAGE is None:
        _DEFAULT_STORAGE = os.path.join(tempfile.gettempdir(),
                                        "rt_workflows")
    os.makedirs(_DEFAULT_STORAGE, exist_ok=True)
    return _DEFAULT_STORAGE


def _wf_dir(workflow_id: str) -> str:
    d = os.path.join(_storage_root(), workflow_id)
    os.makedirs(d, exist_ok=True)
    return d


def _write_meta(workflow_id: str, **fields):
    path = os.path.join(_wf_dir(workflow_id), "meta.pkl")
    meta = {}
    if os.path.exists(path):
        with open(path, "rb") as f:
            meta = pickle.load(f)
    meta.update(fields)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(meta, f)
    os.replace(tmp, path)
    return meta


def _read_meta(workflow_id: str) -> Dict:
    path = os.path.join(_wf_dir(workflow_id), "meta.pkl")
    if not os.path.exists(path):
        return {}
    with open(path, "rb") as f:
        return pickle.load(f)


class _DurableExecutor:
    """Executes a DAG bottom-up, checkpointing each task's output
    (reference: _workflow_task_executor task_executor.py:50)."""

    def __init__(self, workflow_id: str, args, kwargs):
        self.workflow_id = workflow_id
        self.dir = _wf_dir(workflow_id)
        self.args = args
        self.kwargs = kwargs
        self._counters: Dict[str, int] = {}

    def _task_key(self, node: FunctionNode) -> str:
        """Deterministic per-run key: function name + visit index (the
        bottom-up traversal order is deterministic for a given DAG)."""
        name = getattr(node._fn, "__name__", "task")
        idx = self._counters.get(name, 0)
        self._counters[name] = idx + 1
        return f"{name}__{idx}"

    def execute(self, dag: DAGNode):
        def _exec(node, args, kwargs):
            if isinstance(node, InputNode):
                return node._execute_impl(args, kwargs,
                                          {"args": self.args,
                                           "kwargs": self.kwargs})
            if not isinstance(node, FunctionNode):
                raise TypeError(
                    "workflows support function DAGs (fn.bind); got "
                    f"{type(node).__name__}")
            key = self._task_key(node)
            ckpt = os.path.join(self.dir, f"task__{key}.pkl")
            if os.path.exists(ckpt):
                with open(ckpt, "rb") as f:
                    return pickle.load(f)
            # Upstream values were materialized (durability barrier);
            # run this task as a cluster task and persist its output.
            rf = ray_tpu.remote(node._fn)
            if node._bound_options:
                rf = rf.options(**node._bound_options)
            value = ray_tpu.get(rf.remote(*args, **kwargs), timeout=3600)
            tmp = ckpt + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(value, f)
            os.replace(tmp, ckpt)
            return value

        return dag._apply_recursive(_exec)


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None,
        **kwargs) -> Any:
    """Run a DAG durably to completion (reference: api.py:120)."""
    workflow_id = workflow_id or f"workflow_{int(time.time() * 1e6):x}"
    _write_meta(workflow_id, status=STATUS_RUNNING,
                start_ts=time.time())
    try:
        result = _DurableExecutor(workflow_id, args, kwargs).execute(dag)
    except Exception as e:
        _write_meta(workflow_id, status=STATUS_FAILED, error=repr(e),
                    end_ts=time.time())
        raise
    # result.pkl BEFORE the SUCCESSFUL marker: the status contract is
    # "SUCCESSFUL implies a retrievable result".
    ckpt = os.path.join(_wf_dir(workflow_id), "result.pkl")
    tmp = ckpt + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(result, f)
    os.replace(tmp, ckpt)
    _write_meta(workflow_id, status=STATUS_SUCCESSFUL,
                end_ts=time.time())
    return result


def run_async(dag: DAGNode, *args, workflow_id: Optional[str] = None,
              **kwargs):
    """Run in a background task; returns an ObjectRef to the result
    (reference: api.py:166)."""
    workflow_id = workflow_id or f"workflow_{int(time.time() * 1e6):x}"

    # The driver-side closure carries the dag; the task replays it with
    # the same workflow id so checkpoints land in the same directory.
    storage = _storage_root()

    @ray_tpu.remote
    def _drive():
        import ray_tpu.workflow as wf
        wf.init(storage)
        return wf.run(dag, *args, workflow_id=workflow_id, **kwargs)

    return _drive.remote()


def resume(workflow_id: str) -> Any:
    """Return the stored result, or raise if the workflow never finished
    (re-running an unfinished workflow requires its original DAG — call
    run() again with the same workflow_id; completed tasks replay from
    their checkpoints)."""
    path = os.path.join(_wf_dir(workflow_id), "result.pkl")
    if os.path.exists(path):
        with open(path, "rb") as f:
            return pickle.load(f)
    raise RuntimeError(
        f"workflow {workflow_id!r} has no stored result "
        f"(status={get_status(workflow_id)}); re-run its DAG with "
        f"run(dag, workflow_id=...) to continue from checkpoints")


def get_status(workflow_id: str) -> Optional[str]:
    meta = _read_meta(workflow_id)
    status = meta.get("status")
    if status == STATUS_RUNNING and meta.get("end_ts") is None:
        # Crashed mid-run (no end timestamp): resumable.
        return STATUS_RESUMABLE
    return status


def list_all() -> List[Dict]:
    root = _storage_root()
    out = []
    for wid in sorted(os.listdir(root)):
        meta = _read_meta(wid)
        if meta:
            out.append({"workflow_id": wid,
                        "status": get_status(wid)})
    return out


def delete(workflow_id: str):
    import shutil
    shutil.rmtree(os.path.join(_storage_root(), workflow_id),
                  ignore_errors=True)


class EventListener:
    """External-event hookup (reference: workflow/event_listener.py
    EventListener.poll_for_event + api.py wait_for_event).  Subclass and
    implement ``poll_for_event`` (sync or async) to block until the
    event arrives; its return value becomes the node's output."""

    def poll_for_event(self, *args, **kwargs):
        raise NotImplementedError


def wait_for_event(event_listener_cls, *args, **kwargs) -> FunctionNode:
    """A DAG node that completes when the listener's event arrives.

    The received payload is checkpointed like any task output, so a
    resumed workflow replays it WITHOUT waiting for the event again —
    the exactly-once contract events exist for (reference:
    workflow/api.py wait_for_event)."""
    if not (isinstance(event_listener_cls, type)
            and issubclass(event_listener_cls, EventListener)):
        raise TypeError("wait_for_event expects an EventListener "
                        "subclass")

    def _wait(*a, **kw):
        import asyncio
        import inspect
        listener = event_listener_cls()
        res = listener.poll_for_event(*a, **kw)
        if inspect.isawaitable(res):
            loop = asyncio.new_event_loop()
            try:
                res = loop.run_until_complete(res)
            finally:
                loop.close()
        return res

    _wait.__name__ = f"event_{event_listener_cls.__name__}"
    return FunctionNode(_wait, args, kwargs)
