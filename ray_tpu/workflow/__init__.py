"""Workflow: durable execution of task DAGs, continuations, virtual actors.

Reference: python/ray/workflow — workflow.run/run_async (api.py:120,166),
per-task checkpointing in task_executor.py:50 (each task's output is
persisted before dependents run), WorkflowManagementActor
(workflow_access.py:88) tracking status, storage/ for persistence,
virtual actors (durable per-method-journaled actors), and dynamic
sub-workflows (a task RETURNING a DAG continues the workflow with it —
workflow.continuation).

Re-design: the DAG IR is ray_tpu.dag; every node's result is checkpointed
under a deterministic task key through the pluggable byte-storage layer
(ray_tpu.util.storage: local paths, file://, mem://, registered schemes),
so `resume` replays only the tasks whose checkpoints are missing
(exactly-once-ish per task) and the whole workflow state survives the
driver machine when the storage URI points somewhere durable.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, FunctionNode, InputNode
from ray_tpu.util.storage import Storage, get_storage

_DEFAULT_STORAGE: Optional[str] = None

STATUS_RUNNING = "RUNNING"
STATUS_SUCCESSFUL = "SUCCESSFUL"
STATUS_FAILED = "FAILED"
STATUS_RESUMABLE = "RESUMABLE"
STATUS_CANCELED = "CANCELED"


class WorkflowCancellationError(RuntimeError):
    """Raised inside a run when its workflow is canceled (reference:
    workflow.exceptions.WorkflowCancellationError)."""


def init(storage: Optional[str] = None):
    """Set the storage root — a path or URI (reference: workflow.init)."""
    global _DEFAULT_STORAGE
    _DEFAULT_STORAGE = storage


def _storage_uri() -> str:
    global _DEFAULT_STORAGE
    if _DEFAULT_STORAGE is None:
        _DEFAULT_STORAGE = os.path.join(tempfile.gettempdir(),
                                        "rt_workflows")
    return _DEFAULT_STORAGE


_STORE_CACHE: Dict[str, Storage] = {}


def _store() -> Storage:
    uri = _storage_uri()
    st = _STORE_CACHE.get(uri)
    if st is None:
        st = _STORE_CACHE[uri] = get_storage(uri)
    return st


def _put(key: str, value: Any):
    _store().write_bytes(key, pickle.dumps(value))


def _get(key: str, default=None):
    st = _store()
    if not st.exists(key):
        return default
    return pickle.loads(st.read_bytes(key))


def _write_meta(workflow_id: str, **fields):
    key = f"{workflow_id}/meta.pkl"
    meta = _get(key, {}) or {}
    meta.update(fields)
    _put(key, meta)
    return meta


def _read_meta(workflow_id: str) -> Dict:
    return _get(f"{workflow_id}/meta.pkl", {}) or {}


class _DurableExecutor:
    """Executes a DAG bottom-up, checkpointing each task's output
    (reference: _workflow_task_executor task_executor.py:50).  A task
    that RETURNS a DAGNode continues the workflow with that sub-DAG
    (reference: workflow.continuation / dynamic workflows) — the
    sub-DAG's tasks checkpoint under the parent task's key prefix."""

    def __init__(self, workflow_id: str, args, kwargs, prefix: str = ""):
        self.workflow_id = workflow_id
        self.args = args
        self.kwargs = kwargs
        self.prefix = prefix
        self._counters: Dict[str, int] = {}

    def _task_key(self, node: FunctionNode) -> str:
        """Deterministic per-run key: function name + visit index (the
        bottom-up traversal order is deterministic for a given DAG)."""
        name = getattr(node._fn, "__name__", "task")
        idx = self._counters.get(name, 0)
        self._counters[name] = idx + 1
        return f"{self.prefix}{name}__{idx}"

    def execute(self, dag: DAGNode):
        def _exec(node, args, kwargs):
            if isinstance(node, InputNode):
                return node._execute_impl(args, kwargs,
                                          {"args": self.args,
                                           "kwargs": self.kwargs})
            if not isinstance(node, FunctionNode):
                raise TypeError(
                    "workflows support function DAGs (fn.bind); got "
                    f"{type(node).__name__}")
            key = self._task_key(node)
            ckpt = f"{self.workflow_id}/task__{key}.pkl"
            st = _store()
            if st.exists(ckpt):
                return pickle.loads(st.read_bytes(ckpt))
            # Durable cancel barrier: a cancel() from ANY process lands
            # in storage and stops the run before its next task.
            if _read_meta(self.workflow_id).get("status") == \
                    STATUS_CANCELED:
                raise WorkflowCancellationError(
                    f"workflow {self.workflow_id!r} was canceled")
            # Upstream values were materialized (durability barrier);
            # run this task as a cluster task and persist its output.
            rf = ray_tpu.remote(node._fn)
            if node._bound_options:
                rf = rf.options(**node._bound_options)
            value = ray_tpu.get(rf.remote(*args, **kwargs), timeout=3600)
            if isinstance(value, DAGNode):
                # Dynamic sub-workflow: the task decided the next stage
                # at runtime.  Execute it durably under this task's key
                # prefix, checkpoint the FINAL value under this task's
                # key (a resume replays the whole continuation from its
                # own checkpoints).
                sub = _DurableExecutor(self.workflow_id, self.args,
                                       self.kwargs,
                                       prefix=f"{key}.")
                value = sub.execute(value)
            st.write_bytes(ckpt, pickle.dumps(value))
            return value

        return dag._apply_recursive(_exec)


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None,
        **kwargs) -> Any:
    """Run a DAG durably to completion (reference: api.py:120)."""
    workflow_id = workflow_id or f"workflow_{int(time.time() * 1e6):x}"
    _write_meta(workflow_id, status=STATUS_RUNNING,
                start_ts=time.time())
    try:
        result = _DurableExecutor(workflow_id, args, kwargs).execute(dag)
    except WorkflowCancellationError:
        _write_meta(workflow_id, status=STATUS_CANCELED,
                    end_ts=time.time())
        raise
    except Exception as e:
        _write_meta(workflow_id, status=STATUS_FAILED, error=repr(e),
                    end_ts=time.time())
        raise
    # result.pkl BEFORE the SUCCESSFUL marker: the status contract is
    # "SUCCESSFUL implies a retrievable result".
    _put(f"{workflow_id}/result.pkl", result)
    _write_meta(workflow_id, status=STATUS_SUCCESSFUL,
                end_ts=time.time())
    return result


def run_async(dag: DAGNode, *args, workflow_id: Optional[str] = None,
              **kwargs):
    """Run in a background task; returns an ObjectRef to the result
    (reference: api.py:166)."""
    workflow_id = workflow_id or f"workflow_{int(time.time() * 1e6):x}"

    # The driver-side closure carries the dag; the task replays it with
    # the same workflow id so checkpoints land in the same storage.
    storage = _storage_uri()

    @ray_tpu.remote
    def _drive():
        import ray_tpu.workflow as wf
        wf.init(storage)
        return wf.run(dag, *args, workflow_id=workflow_id, **kwargs)

    return _drive.remote()


def resume(workflow_id: str) -> Any:
    """Return the stored result, or raise if the workflow never finished
    (re-running an unfinished workflow requires its original DAG — call
    run() again with the same workflow_id; completed tasks replay from
    their checkpoints)."""
    st = _store()
    key = f"{workflow_id}/result.pkl"
    if st.exists(key):
        return pickle.loads(st.read_bytes(key))
    raise RuntimeError(
        f"workflow {workflow_id!r} has no stored result "
        f"(status={get_status(workflow_id)}); re-run its DAG with "
        f"run(dag, workflow_id=...) to continue from checkpoints")


def get_status(workflow_id: str) -> Optional[str]:
    meta = _read_meta(workflow_id)
    status = meta.get("status")
    if status == STATUS_RUNNING and meta.get("end_ts") is None:
        # Crashed mid-run (no end timestamp): resumable.
        return STATUS_RESUMABLE
    return status


def list_all() -> List[Dict]:
    st = _store()
    seen = set()
    out = []
    for key in st.list_prefix(""):
        wid = key.split("/", 1)[0]
        if wid in seen or not wid:
            continue
        seen.add(wid)
        if _read_meta(wid):
            out.append({"workflow_id": wid, "status": get_status(wid)})
    return out


def delete(workflow_id: str):
    _store().delete_prefix(workflow_id)


def cancel(workflow_id: str) -> None:
    """Durably cancel a workflow (reference: workflow.cancel).  The
    marker lands in storage, so the running driver — even in another
    process — stops before launching its next task; completed task
    checkpoints are kept (delete() removes them)."""
    meta = _read_meta(workflow_id)
    if not meta:
        raise KeyError(f"no workflow {workflow_id!r}")
    if meta.get("status") == STATUS_SUCCESSFUL:
        raise RuntimeError(
            f"workflow {workflow_id!r} already finished successfully")
    _write_meta(workflow_id, status=STATUS_CANCELED, end_ts=time.time())


def get_output(workflow_id: str, *, timeout: Optional[float] = None) -> Any:
    """Block until the workflow reaches a terminal state, then return
    its stored result (reference: workflow.get_output).

    A RESUMABLE workflow (driver crashed mid-run) is indistinguishable
    from one still running — status metadata alone can't tell a live
    driver from a dead one — so this waits; pass `timeout` when the
    driver may have died, then resume() / re-run() it."""
    deadline = None if timeout is None else time.time() + timeout
    while True:
        status = get_status(workflow_id)
        if status == STATUS_SUCCESSFUL:
            return resume(workflow_id)
        if status in (STATUS_FAILED, STATUS_CANCELED):
            meta = _read_meta(workflow_id)
            raise RuntimeError(
                f"workflow {workflow_id!r} ended {status}: "
                f"{meta.get('error', '')}")
        if status is None:
            raise KeyError(f"no workflow {workflow_id!r}")
        if deadline is not None and time.time() > deadline:
            raise TimeoutError(
                f"workflow {workflow_id!r} still {status} after "
                f"{timeout}s")
        time.sleep(0.1)


# --------------------------------------------------------- virtual actors
# Reference: the workflow virtual-actor API (durable actors whose state
# is journaled per method call; workflow_access.py get_actor).  State
# versions live in storage: a handle on ANY machine resumes the actor
# from its latest version; each mutating call persists state BEFORE the
# result is returned.


def _vactor_step(cls_blob, state, method_name, args, kwargs):
    import cloudpickle
    cls = cloudpickle.loads(cls_blob)
    obj = cls.__new__(cls)
    obj.__dict__.update(state)
    result = getattr(obj, method_name)(*args, **kwargs)
    return dict(obj.__dict__), result


class _VirtualMethod:
    def __init__(self, handle: "VirtualActorHandle", name: str):
        self._handle = handle
        self._name = name

    def run(self, *args, **kwargs):
        return self._handle._call(self._name, args, kwargs)

    # Parity alias with the reference's .run_async().run() pairing.
    __call__ = run


class VirtualActorHandle:
    def __init__(self, cls, actor_id: str, init_args, init_kwargs):
        self._cls = cls
        self.actor_id = actor_id
        self._prefix = f"virtual_actors/{actor_id}"
        st = _store()
        has_state = (st.exists(f"{self._prefix}/state.pkl")
                     or any("/state.v" in k
                            for k in st.list_prefix(self._prefix)))
        if not has_state:
            obj = cls(*init_args, **init_kwargs)
            self._save(dict(obj.__dict__), version=0)

    def _save(self, state: dict, version: int) -> bool:
        """Claim `version` by exclusive create of its key; False = another
        handle won the version (compare-and-swap, lost-update-proof on
        backends with atomic create — LocalStorage/MemStorage in-tree)."""
        import pickle
        blob = pickle.dumps({"state": state, "version": version,
                             "cls": self._cls.__name__})
        won = _store().write_bytes_if_absent(
            f"{self._prefix}/state.v{version:08d}.pkl", blob)
        if won:
            # GC old versions (keep a small window so a concurrent
            # reader's max(keys) never dangles mid-listing); bounds both
            # storage and per-call list_prefix cost.
            st = _store()
            old = sorted(k for k in st.list_prefix(self._prefix)
                         if "/state.v" in k)[:-4]
            for k in old:
                try:
                    st.delete(k)
                except (NotImplementedError, OSError, KeyError):
                    break
        return won

    def _load(self) -> dict:
        keys = [k for k in _store().list_prefix(self._prefix)
                if "/state.v" in k]
        return _get(max(keys)) if keys else _get(
            f"{self._prefix}/state.pkl")

    def _call(self, method_name: str, args, kwargs):
        readonly = getattr(getattr(self._cls, method_name, None),
                           "_workflow_readonly", False)
        import cloudpickle
        step = ray_tpu.remote(_vactor_step)
        # The class ships BY VALUE: driver-script (__main__) classes
        # aren't importable on workers.
        cls_blob = cloudpickle.dumps(self._cls)
        if readonly:
            snap = self._load()
            _, result = ray_tpu.get(
                step.remote(cls_blob, snap["state"], method_name, args,
                            kwargs), timeout=3600)
            return result
        for _ in range(16):
            snap = self._load()
            new_state, result = ray_tpu.get(  # noqa: RTL001 (each retry depends on persisted state)
                step.remote(cls_blob, snap["state"], method_name, args,
                            kwargs), timeout=3600)
            # Persist state BEFORE surfacing the result: a crash after
            # this point re-reads the already-updated state; a crash
            # before it replays the method (at-least-once, like the
            # reference's journaled virtual actors).  The exclusive
            # create claims version N+1; losing the claim means another
            # handle interleaved (resume-from-any-machine), so replay
            # against its state rather than silently dropping an update.
            if self._save(new_state, snap["version"] + 1):
                return result
        raise RuntimeError(
            f"virtual actor {self.actor_id}.{method_name}: too many "
            "concurrent-update conflicts")

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _VirtualMethod(self, name)


def virtual_actor(cls):
    """Class decorator: adds `get_or_create(actor_id, *init_args)`
    returning a durable handle (reference: workflow virtual actors)."""

    def get_or_create(actor_id: str, *init_args, **init_kwargs):
        return VirtualActorHandle(cls, actor_id, init_args, init_kwargs)

    cls.get_or_create = staticmethod(get_or_create)
    return cls


def readonly(fn):
    """Mark a virtual-actor method as non-mutating: its calls skip the
    state write (reference: @workflow.virtual_actor.readonly)."""
    fn._workflow_readonly = True
    return fn


class EventListener:
    """External-event hookup (reference: workflow/event_listener.py
    EventListener.poll_for_event + api.py wait_for_event).  Subclass and
    implement ``poll_for_event`` (sync or async) to block until the
    event arrives; its return value becomes the node's output."""

    def poll_for_event(self, *args, **kwargs):
        raise NotImplementedError


def wait_for_event(event_listener_cls, *args, **kwargs) -> FunctionNode:
    """A DAG node that completes when the listener's event arrives.

    The received payload is checkpointed like any task output, so a
    resumed workflow replays it WITHOUT waiting for the event again —
    the exactly-once contract events exist for (reference:
    workflow/api.py wait_for_event)."""
    if not (isinstance(event_listener_cls, type)
            and issubclass(event_listener_cls, EventListener)):
        raise TypeError("wait_for_event expects an EventListener "
                        "subclass")

    def _wait(*a, **kw):
        import asyncio
        import inspect
        listener = event_listener_cls()
        res = listener.poll_for_event(*a, **kw)
        if inspect.isawaitable(res):
            loop = asyncio.new_event_loop()
            try:
                res = loop.run_until_complete(res)
            finally:
                loop.close()
        return res

    _wait.__name__ = f"event_{event_listener_cls.__name__}"
    return FunctionNode(_wait, args, kwargs)


def continuation(dag: DAGNode) -> DAGNode:
    """Explicit marker for dynamic sub-workflows (reference:
    workflow.continuation).  Returning a DAG from a workflow task already
    continues with it; this exists for API parity and readability."""
    return dag

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("workflow")
del _rlu
