"""Fixed-shape tensor columns for Arrow tables.

Reference: python/ray/air/util/tensor_extensions/arrow.py
(ArrowTensorType / ArrowTensorArray) — multi-dimensional ndarrays as
first-class table columns, so image / embedding / activation data flows
through Data blocks, Parquet files, and batch formats without
object-dtype fallbacks.  Re-designed minimal: one extension type backed
by a FixedSizeListArray of the flattened elements, with zero-copy
to_numpy both ways for primitive dtypes.

The extension is registered with pyarrow once at import, so Parquet and
IPC round-trips reconstruct `ArrowTensorType` automatically from the
serialized metadata.
"""

from __future__ import annotations

import json
from typing import Sequence, Union

import numpy as np
import pyarrow as pa

_EXT_NAME = "ray_tpu.data.tensor"


class ArrowTensorType(pa.ExtensionType):
    """Arrow extension type for a column of fixed-shape tensors.

    `shape` is the PER-ELEMENT shape (row count excluded); storage is a
    FixedSizeList<value_type>[prod(shape)].
    """

    def __init__(self, shape: Sequence[int], value_type: pa.DataType):
        self._shape = tuple(int(s) for s in shape)
        size = int(np.prod(self._shape)) if self._shape else 1
        super().__init__(pa.list_(value_type, size), _EXT_NAME)

    @property
    def shape(self):
        return self._shape

    @property
    def value_type(self) -> pa.DataType:
        return self.storage_type.value_type

    def to_pandas_dtype(self):
        return np.object_

    def __arrow_ext_serialize__(self) -> bytes:
        return json.dumps({"shape": list(self._shape)}).encode()

    @classmethod
    def __arrow_ext_deserialize__(cls, storage_type, serialized):
        shape = json.loads(serialized.decode())["shape"]
        return cls(shape, storage_type.value_type)

    def __arrow_ext_class__(self):
        return ArrowTensorArray

    def __reduce__(self):
        return (ArrowTensorType,
                (self._shape, self.storage_type.value_type))


class ArrowTensorArray(pa.ExtensionArray):
    """Column of fixed-shape tensors (reference: ArrowTensorArray)."""

    @staticmethod
    def from_numpy(arr: np.ndarray) -> "ArrowTensorArray":
        """(n, *shape) ndarray -> extension array of n tensors.  The
        element buffer is handed to Arrow without a copy for primitive
        C-contiguous input."""
        arr = np.ascontiguousarray(arr)
        if arr.ndim < 2:
            raise ValueError(
                "from_numpy expects an (n, ...) array with at least one "
                f"tensor dimension, got shape {arr.shape}")
        n = arr.shape[0]
        shape = arr.shape[1:]
        flat = arr.reshape(n, -1).reshape(-1)
        values = pa.array(flat)
        size = int(np.prod(shape))
        storage = pa.FixedSizeListArray.from_arrays(values, size)
        typ = ArrowTensorType(shape, values.type)
        return pa.ExtensionArray.from_storage(typ, storage)

    def to_numpy(self, zero_copy_only: bool = False) -> np.ndarray:
        typ: ArrowTensorType = self.type
        flat = self.storage.flatten()
        values = flat.to_numpy(zero_copy_only=zero_copy_only)
        return values.reshape((len(self),) + typ.shape)


def tensor_column_to_numpy(col: Union[pa.ChunkedArray, pa.Array]
                           ) -> np.ndarray:
    """ChunkedArray/Array of ArrowTensorType -> stacked (n, *shape)."""
    if isinstance(col, pa.ChunkedArray):
        chunks = [c.to_numpy(zero_copy_only=False) for c in col.chunks]
        if len(chunks) == 1:
            return chunks[0]
        return np.concatenate(chunks, axis=0)
    return col.to_numpy(zero_copy_only=False)


def is_tensor_type(t: pa.DataType) -> bool:
    return isinstance(t, ArrowTensorType)


def _register():
    try:
        pa.register_extension_type(ArrowTensorType((1,), pa.float32()))
    except pa.ArrowKeyError:
        pass  # already registered (module re-import)


_register()
