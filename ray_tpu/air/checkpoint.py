"""Checkpoint: one object interchangeable between dict <-> directory <->
bytes, with native jax-pytree support.

Reference semantics: python/ray/air/checkpoint.py:42 (dict/dir/URI
interconversion).  TPU-era redesign: the payload of a training checkpoint
is a jax pytree of (possibly sharded) arrays; `from_pytree`/`to_pytree`
fetch shards to host and store them msgpack/npz-style so a checkpoint
written from a sharded mesh restores on any topology.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tarfile
import tempfile
import io
from typing import Any, Optional

_DICT_FILE = "checkpoint_dict.pkl"
_PYTREE_FILE = "pytree.npz"
_PYTREE_DEF = "pytree_def.pkl"


class Checkpoint:
    """Immutable carrier of training state."""

    def __init__(self, data: Optional[dict] = None,
                 local_path: Optional[str] = None):
        if (data is None) == (local_path is None):
            raise ValueError("pass exactly one of data / local_path")
        self._data = data
        self._local_path = local_path

    # -- constructors -------------------------------------------------
    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(local_path=os.path.abspath(path))

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        return cls(data=pickle.loads(blob))

    @classmethod
    def from_pytree(cls, tree: Any, extra: Optional[dict] = None
                    ) -> "Checkpoint":
        """Store a jax pytree (device arrays are fetched to host)."""
        import jax
        import numpy as np
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        host = [np.asarray(jax.device_get(x)) for x in leaves]
        return cls(data={"__pytree_leaves__": host,
                         "__pytree_def__": treedef,
                         **(extra or {})})

    # -- views --------------------------------------------------------
    def to_dict(self) -> dict:
        if self._data is not None:
            return dict(self._data)
        d = {}
        p = os.path.join(self._local_path, _DICT_FILE)
        if os.path.exists(p):
            with open(p, "rb") as f:
                d = pickle.load(f)
        return d

    def to_pytree(self, sharding_tree: Any = None) -> Any:
        """Rebuild the stored pytree; optionally device_put each leaf with
        the matching sharding from `sharding_tree` (restore onto a new
        mesh topology)."""
        d = self.to_dict()
        if "__pytree_leaves__" not in d:
            raise ValueError("checkpoint holds no pytree")
        import jax
        leaves, treedef = d["__pytree_leaves__"], d["__pytree_def__"]
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
        if sharding_tree is not None:
            tree = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, s), tree, sharding_tree)
        return tree

    def extra(self) -> dict:
        return {k: v for k, v in self.to_dict().items()
                if not k.startswith("__pytree_")}

    def to_bytes(self) -> bytes:
        return pickle.dumps(self.to_dict())

    def to_directory(self, path: Optional[str] = None) -> str:
        path = path or tempfile.mkdtemp(prefix="rt_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._local_path is not None:
            if os.path.abspath(path) != self._local_path:
                shutil.copytree(self._local_path, path, dirs_exist_ok=True)
        else:
            with open(os.path.join(path, _DICT_FILE), "wb") as f:
                pickle.dump(self._data, f)
        return path

    # -- uri / archive ------------------------------------------------
    def to_uri(self, uri: str) -> str:
        """Persist to a file:// URI (cloud schemes gated: no egress here)."""
        if uri.startswith("file://"):
            dest = uri[len("file://"):]
        elif "://" not in uri:
            dest = uri
        else:
            raise NotImplementedError(
                f"scheme of {uri!r} not available in this environment")
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        buf = io.BytesIO()
        with tempfile.TemporaryDirectory() as tmp:
            self.to_directory(tmp)
            with tarfile.open(fileobj=buf, mode="w") as tar:
                tar.add(tmp, arcname=".")
        with open(dest, "wb") as f:
            f.write(buf.getvalue())
        return uri

    @classmethod
    def from_uri(cls, uri: str) -> "Checkpoint":
        src = uri[len("file://"):] if uri.startswith("file://") else uri
        tmp = tempfile.mkdtemp(prefix="rt_ckpt_")
        with tarfile.open(src, mode="r") as tar:
            tar.extractall(tmp, filter="data")
        return cls.from_directory(tmp)

    def __repr__(self):
        kind = "dict" if self._data is not None else f"dir:{self._local_path}"
        return f"Checkpoint({kind})"
