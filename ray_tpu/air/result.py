"""Result: the outcome of one trial/run (reference: python/ray/air/result.py)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.air.checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint] = None
    error: Optional[Exception] = None
    path: str = ""
    metrics_dataframe: Any = None
    best_checkpoints: Optional[List[Tuple[Checkpoint, Dict]]] = None
    config: Optional[Dict] = None

    @property
    def done(self) -> bool:
        return self.error is None
