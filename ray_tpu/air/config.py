"""Run-level configuration vocabulary (reference: python/ray/air/config.py
ScalingConfig/RunConfig/FailureConfig/CheckpointConfig).

TPU-era extension: ScalingConfig declares mesh parallelism axes
(dp/fsdp/tp/pp/sp/ep) directly — the trainer turns them into a
jax.sharding.Mesh over the gang's chips (SURVEY.md §2.4 implication).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from ray_tpu.parallel.mesh import MeshSpec


@dataclasses.dataclass
class ScalingConfig:
    """How many workers, what each owns, and how the mesh is carved."""
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    # mesh axes (per-gang, across all chips owned by all workers)
    dp: Optional[int] = None
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    # Elastic data-parallel recovery (train/elastic.py): on member
    # death (or a granted resize) the gang re-forms at the new world
    # size and re-shards in-memory state over the collective plane
    # instead of cold-restarting the trial from the last checkpoint.
    # elastic_min_workers is the survivor quorum below which recovery
    # falls back to the cold restart (None: RT_TRAIN_ELASTIC_MIN_WORKERS,
    # default 1).  In-place recoveries do NOT consume
    # FailureConfig.max_failures — that budget counts cold restarts.
    elastic: bool = False
    elastic_min_workers: Optional[int] = None
    # Cluster-autopilot declaration (_private/arbiter.py): the gang
    # registers with the GCS broker under ``train:<name>`` (a random
    # name when unset — set one to target it with `rt resize`), and
    # ``priority`` orders it against other gangs when a serve SLO
    # breach forces a reclaim (lowest priority shrinks first).
    name: Optional[str] = None
    priority: int = 50

    @property
    def _resources(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            return dict(self.resources_per_worker)
        return {"TPU": 1.0} if self.use_tpu else {"CPU": 1.0}

    def mesh_spec(self, n_devices: int) -> MeshSpec:
        if self.dp is not None:
            return MeshSpec(dp=self.dp, fsdp=self.fsdp, tp=self.tp,
                            pp=self.pp, sp=self.sp, ep=self.ep)
        return MeshSpec.infer(n_devices, tp=self.tp, pp=self.pp,
                              sp=self.sp, ep=self.ep, fsdp=self.fsdp)

    def as_placement_group_factory(self):
        from ray_tpu.tune.execution.placement_groups import (
            PlacementGroupFactory)
        bundles = [self._resources for _ in range(self.num_workers)]
        return PlacementGroupFactory(bundles,
                                     strategy=self.placement_strategy)


@dataclasses.dataclass
class DatasetConfig:
    """Per-dataset ingest behavior for DataParallelTrainer (reference:
    air/config.py DatasetConfig + its fill_defaults: the "train" dataset
    splits across workers and fits the preprocessor; aux datasets ship
    whole to every worker).  None fields mean "use the role default"."""

    fit: Optional[bool] = None          # fit the trainer's preprocessor?
    split: Optional[bool] = None        # shard across workers?
    required: Optional[bool] = None     # error if absent?
    transform: Optional[bool] = None    # apply the fitted preprocessor?
    global_shuffle: bool = False        # random_shuffle before ingest
    # Seed for global_shuffle — with the streaming ingest path each
    # epoch's shuffle derives from (shuffle_seed, epoch), so a fixed
    # seed reproduces the exact batch sequence (Dataset.random_shuffle
    # is deterministic per seed regardless of parallelism).
    shuffle_seed: Optional[int] = None

    @staticmethod
    def validated(dataset_config: Optional[dict], datasets: dict
                  ) -> dict:
        """Merge user overrides onto role defaults for every dataset."""
        merged = {}
        for name in datasets:
            is_train = name == "train"
            dc = (dataset_config or {}).get(name) or DatasetConfig()
            merged[name] = DatasetConfig(
                fit=dc.fit if dc.fit is not None else is_train,
                split=dc.split if dc.split is not None else is_train,
                required=bool(dc.required),
                transform=dc.transform if dc.transform is not None
                else True,
                global_shuffle=dc.global_shuffle,
                shuffle_seed=dc.shuffle_seed)
        for name, dc in (dataset_config or {}).items():
            if dc and dc.required and name not in datasets:
                raise ValueError(
                    f"dataset {name!r} is required but was not passed "
                    f"to the trainer (got: {sorted(datasets)})")
        return merged


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0
    fail_fast: bool = False


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = False


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    stop: Optional[Any] = None
    verbose: int = 1
    # tune.logger.Callback instances (JsonLoggerCallback,
    # CSVLoggerCallback, TBXLoggerCallback, or user-defined) —
    # reference: air.RunConfig(callbacks=[...]).
    callbacks: Optional[list] = None
