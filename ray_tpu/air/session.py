"""Training/tuning session: the worker-side reporting channel.

Reference: python/ray/air/session.py + train/_internal/session.py:103-220
(thread + queue handoff between the user loop and the harness).  The user's
train function runs in a thread inside a worker actor; `session.report`
enqueues (metrics, checkpoint) for the harness to consume; rank/mesh
context comes from the backend that started the worker.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Optional

from ray_tpu.air.checkpoint import Checkpoint


class _Session:
    def __init__(self, world_rank: int = 0, world_size: int = 1,
                 local_rank: int = 0, trial_name: str = "",
                 trial_id: str = "", mesh: Any = None,
                 checkpoint: Optional[Checkpoint] = None,
                 trial_dir: str = ""):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.trial_name = trial_name
        self.trial_id = trial_id
        self.mesh = mesh
        self.trial_dir = trial_dir
        self.loaded_checkpoint = checkpoint
        self.dataset_shards: dict = {}
        self.result_queue: "queue.Queue" = queue.Queue()
        self.continue_event = threading.Event()
        self.stop_requested = False
        self.iteration = 0
        # Elastic-training state (train/elastic.py): the re-form
        # generation this session last joined, the generation a pending
        # recovery targets (set by the worker's agent thread to unwind
        # a report-blocked loop), the user's in-memory resume stash,
        # and how many in-place resizes this worker survived.
        self.elastic_gen = 0
        self.reform_pending_gen = 0
        self._elastic_state: Optional[dict] = None
        self.elastic_resizes = 0

    def report(self, metrics: dict, checkpoint: Optional[Checkpoint] = None):
        self.iteration += 1
        self.result_queue.put((dict(metrics), checkpoint))
        # Block the user thread until the harness consumed the result —
        # keeps reporting lossless and backpressured (reference:
        # train/_internal/session.py pause-on-report semantics).
        self.continue_event.wait()
        self.continue_event.clear()
        if self.reform_pending_gen > self.elastic_gen:
            # The gang is re-forming and this loop was parked in report
            # (not in a collective op, where the group abort would have
            # reached it) — unwind into the elastic rejoin path.
            from ray_tpu.train.elastic import ElasticReset
            raise ElasticReset("gang re-forming (recovery generation "
                               f"{self.reform_pending_gen})")
        if self.stop_requested:
            raise StopIteration("session stopped")


_session_lock = threading.Lock()
_sessions: dict[int, _Session] = {}


def _set_session(s: Optional[_Session]):
    with _session_lock:
        if s is None:
            _sessions.pop(threading.get_ident(), None)
        else:
            _sessions[threading.get_ident()] = s


def _get_session() -> Optional[_Session]:
    return _sessions.get(threading.get_ident())


def _require() -> _Session:
    s = _get_session()
    if s is None:
        raise RuntimeError("no active train/tune session in this thread")
    return s


# -- public API (reference: air/session.py) ---------------------------

def report(metrics: dict, checkpoint: Optional[Checkpoint] = None) -> None:
    _require().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _require().loaded_checkpoint


def get_world_rank() -> int:
    return _require().world_rank


def get_world_size() -> int:
    return _require().world_size


def get_local_rank() -> int:
    return _require().local_rank


def get_trial_name() -> str:
    return _require().trial_name


def get_trial_id() -> str:
    return _require().trial_id


def get_collective_group() -> Optional[str]:
    """Name of the host collective group the BackendExecutor created
    across this training gang (every rank is already a member), or
    None for single-worker runs / externally-managed gangs.  Use it
    with ray_tpu.util.collective (or train.allreduce_gradients) for
    data-parallel gradient / statistics sync on the transfer plane."""
    import os
    return os.environ.get("RT_TRAIN_COLLECTIVE_GROUP") or None


def stash_elastic_state(state: dict) -> None:
    """Stash this rank's in-memory resume state (model/optimizer
    arrays, step counter, RNG...) for elastic recovery.  Call it once
    per step AFTER the optimizer update: when the gang re-forms at a
    new world size, the authoritative survivor's stash is broadcast
    over the collective data plane and every rank resumes from it —
    no checkpoint round trip.  Include a ``"step"`` key: the recovery
    rolls the gang back to the LOWEST stashed step (the only state
    every rank is guaranteed to have contributed to).  Loops that
    never stash still recover elastically, but re-enter from the last
    checkpoint instead."""
    _require()._elastic_state = dict(state)


def get_elastic_state() -> Optional[dict]:
    """The resume stash adopted during the last elastic re-form (or
    this rank's own most recent stash), None on a fresh start.  A
    re-entered train loop should prefer this over ``get_checkpoint()``
    and resume at ``state["step"] + 1``."""
    return _require()._elastic_state


def get_dataset_shard(name: str = "train"):
    """This rank's shard of a Dataset passed to the trainer via
    `datasets=` (reference: air/session.py get_dataset_shard — the
    last-mile Data -> Train ingest)."""
    shard = _require().dataset_shards.get(name)
    if shard is None:
        raise KeyError(
            f"no dataset {name!r} was passed to the trainer "
            f"(available: {sorted(_require().dataset_shards)})")
    return shard


def get_trial_dir() -> str:
    return _require().trial_dir


def get_mesh():
    """TPU-native: the jax Mesh this worker's gang trains over (None when
    the backend didn't build one)."""
    return _require().mesh
