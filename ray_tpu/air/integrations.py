"""Experiment-tracker integrations: W&B, Comet, and MLflow logger
callbacks.

Reference: python/ray/air/integrations/wandb.py (WandbLoggerCallback —
one tracker run per trial, metrics on result, config as run config)
and python/ray/air/integrations/mlflow.py (MLflowLoggerCallback —
one mlflow run per trial, params at start, metrics per step).

Both ride this repo's ``tune.logger.LoggerCallback`` seam.  The
tracker client is INJECTABLE (``module=``): tests drive the full
callback protocol with a fake module, and real ``wandb``/``mlflow``
are picked up automatically when installed — the callbacks never make
the libraries a hard dependency (same lazy posture as the
reference's ``_import_wandb`` guards).
"""

from __future__ import annotations

import numbers
from typing import Dict, Optional

from ray_tpu.tune.logger import LoggerCallback, _flatten


def _numeric_only(result: Dict) -> Dict:
    return {k: float(v) for k, v in _flatten(result).items()
            if isinstance(v, numbers.Number)
            and not isinstance(v, bool)}


class WandbLoggerCallback(LoggerCallback):
    """One W&B run per trial (reference: integrations/wandb.py
    WandbLoggerCallback): trial config -> run config, numeric results
    -> ``run.log`` at training_iteration steps."""

    def __init__(self, project: Optional[str] = None,
                 group: Optional[str] = None, module=None, **init_kw):
        super().__init__()
        if module is None:
            try:
                import wandb as module  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "WandbLoggerCallback requires wandb (or pass "
                    "module= explicitly)") from e
        self._wandb = module
        self._project, self._group = project, group
        self._init_kw = init_kw
        self._runs: Dict[str, object] = {}

    def log_trial_start(self, trial) -> None:
        kw = dict(project=self._project, group=self._group,
                  name=trial.name, id=trial.trial_id,
                  config=dict(trial.config), **self._init_kw)
        try:
            # wandb >= 0.19: multiple simultaneous runs in one
            # process.  Plain reinit=True would FINISH the previous
            # trial's run on each init.
            run = self._wandb.init(reinit="create_new", **kw)
        except (TypeError, ValueError):
            if self._runs:
                import logging
                logging.getLogger(__name__).warning(
                    "this wandb version cannot hold concurrent runs in "
                    "one process; starting trial %s will end the %d "
                    "still-open run(s)", trial.trial_id, len(self._runs))
            run = self._wandb.init(reinit=True, **kw)
        self._runs[trial.trial_id] = run

    def log_trial_result(self, iteration, trial, result) -> None:
        run = self._runs.get(trial.trial_id)
        if run is not None:
            run.log(_numeric_only(result), step=iteration)

    def log_trial_end(self, trial, failed: bool = False) -> None:
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.finish(exit_code=1 if failed else 0)

    def on_experiment_end(self, trials) -> None:
        for run in self._runs.values():
            run.finish()
        self._runs.clear()


class CometLoggerCallback(LoggerCallback):
    """One Comet experiment per trial (reference:
    air/integrations/comet.py CometLoggerCallback): trial config ->
    logged parameters, numeric results -> per-step metrics."""

    def __init__(self, project_name: Optional[str] = None,
                 workspace: Optional[str] = None, module=None, **kw):
        super().__init__()
        if module is None:
            try:
                import comet_ml as module  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "CometLoggerCallback requires comet_ml (or pass "
                    "module= explicitly)") from e
        self._comet = module
        self._project, self._workspace = project_name, workspace
        self._kw = kw
        self._experiments: Dict[str, object] = {}

    def log_trial_start(self, trial) -> None:
        exp = self._comet.Experiment(
            project_name=self._project, workspace=self._workspace,
            **self._kw)
        exp.set_name(trial.name)
        exp.log_parameters(_flatten(trial.config))
        self._experiments[trial.trial_id] = exp

    def log_trial_result(self, iteration, trial, result) -> None:
        exp = self._experiments.get(trial.trial_id)
        if exp is not None:
            exp.log_metrics(_numeric_only(result), step=iteration)

    def log_trial_end(self, trial, failed: bool = False) -> None:
        exp = self._experiments.pop(trial.trial_id, None)
        if exp is not None:
            exp.end()

    def on_experiment_end(self, trials) -> None:
        for exp in self._experiments.values():
            exp.end()
        self._experiments.clear()


class MLflowLoggerCallback(LoggerCallback):
    """One MLflow run per trial (reference: integrations/mlflow.py
    MLflowLoggerCallback): config -> params at start, numeric results
    -> per-step metrics, terminal status on end.  Uses the explicit
    ``MlflowClient`` interface (like the reference) so concurrently
    open trial runs never fight over a fluent 'active run'."""

    def __init__(self, tracking_uri: Optional[str] = None,
                 experiment_name: Optional[str] = None, client=None):
        super().__init__()
        if client is None:
            try:
                from mlflow.tracking import MlflowClient
            except ImportError as e:
                raise RuntimeError(
                    "MLflowLoggerCallback requires mlflow (or pass "
                    "client= explicitly)") from e
            client = MlflowClient(tracking_uri)
        self._client = client
        self._experiment_id = "0"
        if experiment_name:
            exp = self._client.get_experiment_by_name(experiment_name)
            self._experiment_id = (
                exp.experiment_id if exp is not None
                else self._client.create_experiment(experiment_name))
        self._runs: Dict[str, str] = {}  # trial_id -> run_id

    def log_trial_start(self, trial) -> None:
        run = self._client.create_run(
            self._experiment_id, tags={"trial_name": trial.name})
        run_id = run.info.run_id
        self._runs[trial.trial_id] = run_id
        for k, v in _flatten(trial.config).items():
            self._client.log_param(run_id, k, v)

    def log_trial_result(self, iteration, trial, result) -> None:
        run_id = self._runs.get(trial.trial_id)
        if run_id is None:
            return
        flat = _numeric_only(result)
        # One request, not one per key — N metrics against a remote
        # tracking server would otherwise cost N round-trips on the
        # driver's run loop (reference batches for the same reason).
        if hasattr(self._client, "log_batch"):
            try:
                import time

                from mlflow.entities import Metric
                ts = int(time.time() * 1000)
                self._client.log_batch(run_id, metrics=[
                    Metric(k, v, ts, iteration)
                    for k, v in flat.items()])
                return
            except ImportError:
                pass
        for k, v in flat.items():
            self._client.log_metric(run_id, k, v, step=iteration)

    def log_trial_end(self, trial, failed: bool = False) -> None:
        run_id = self._runs.pop(trial.trial_id, None)
        if run_id is not None:
            self._client.set_terminated(
                run_id, status="FAILED" if failed else "FINISHED")

    def on_experiment_end(self, trials) -> None:
        for run_id in self._runs.values():
            self._client.set_terminated(run_id, status="FINISHED")
        self._runs.clear()
