"""AIR-equivalent primitives: the shared ML-layer vocabulary.

Reference: python/ray/air — Checkpoint (air/checkpoint.py:42), session
(air/session.py), configs (air/config.py).  Here Checkpoint speaks jax
pytrees natively (orbax-compatible directory layout) and ScalingConfig
declares TPU mesh axes instead of GPU counts.
"""

from ray_tpu.air.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.air.config import (  # noqa: F401
    CheckpointConfig,
    DatasetConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air import session  # noqa: F401
from ray_tpu.air.result import Result  # noqa: F401

# ray_tpu.air.integrations (W&B/MLflow callbacks) is an explicit
# on-demand import, like the reference's ray.air.integrations — an
# eager import here would pull all of ray_tpu.tune into every worker
# that imports air.session.
