"""Per-tenant QoS at the router: token-bucket admission, weighted fair
queueing, and load shedding.

Reference posture (PAPER.md L10 serve controller/router): one hot tenant
must not inflate every other tenant's p99.  Three mechanisms, all at the
admission edge (BEFORE a request occupies replica capacity):

  * token bucket per tenant — sustained rate `rate` tokens/s with
    `burst` headroom; an empty bucket sheds the request immediately
    with :class:`TenantThrottled` ("rate_limited") + a Retry-After
    hint, instead of letting it queue;
  * per-tenant queue cap — a tenant may hold at most `max_queued`
    waiters in the router's line; past that, "queue_full" shed (the
    hot tenant's own backlog, not a shared one);
  * weighted fair queueing — when replicas saturate, waiting requests
    are dispatched by start-time fair queueing over per-tenant virtual
    finish tags, so a tenant with weight w gets ~w/(Σweights) of the
    freed slots no matter how deep any single tenant's backlog is.

Shedding is accounted in `serve_tenant_shed_total` so the soak bench
can assert sheds == rejections observed at the client.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

from ray_tpu.serve.exceptions import TenantThrottled
from ray_tpu.util import metrics as _metrics

TENANT_SHED_COUNTER = _metrics.Counter(
    "serve_tenant_shed_total",
    "Requests shed by per-tenant QoS admission (rate_limited|queue_full)",
    tag_keys=("deployment", "tenant", "reason"))

DEFAULT_TENANT = "default"


class _Bucket:
    __slots__ = ("tokens", "last")

    def __init__(self, burst: float, now: float):
        self.tokens = burst
        self.last = now


class TenantQoS:
    """Admission policy state for ONE deployment's router.

    Single-owner discipline: every method runs on the owning router's
    event loop (admission, WFQ tags, dispatch accounting), so no lock
    is needed.  `rate == 0` disables the token bucket while keeping
    WFQ + queue caps active."""

    def __init__(self, *, rate: float = 0.0,
                 burst: Optional[float] = None,
                 weights: Optional[Dict[str, float]] = None,
                 max_queued: int = 128,
                 default_weight: float = 1.0):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None
                           else max(1.0, self.rate))
        self.weights = dict(weights or {})
        self.max_queued = int(max_queued)
        self.default_weight = float(default_weight)
        self._buckets: Dict[str, _Bucket] = {}
        # Start-time fair queueing state: a global virtual clock plus
        # each tenant's last-issued finish tag.
        self._vclock = 0.0
        self._finish: Dict[str, float] = {}
        self.shed_total = 0  # local tally (bench cross-checks the metric)

    @classmethod
    def from_env(cls) -> Optional["TenantQoS"]:
        """Build the process-default QoS policy from RT_SERVE_* env
        knobs; returns None (QoS off — the router keeps its legacy
        admission path) unless explicitly enabled via RT_SERVE_QOS=1 or
        implied by a nonzero RT_SERVE_TENANT_RATE / a weight table."""
        if os.environ.get("RT_SERVE_QOS", "") == "0":
            return None
        rate = float(os.environ.get("RT_SERVE_TENANT_RATE", "0") or 0)
        weights_spec = os.environ.get("RT_SERVE_TENANT_WEIGHTS", "")
        enabled = (os.environ.get("RT_SERVE_QOS", "") == "1"
                   or rate > 0 or bool(weights_spec))
        if not enabled:
            return None
        weights: Dict[str, float] = {}
        for part in weights_spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, w = part.partition(":")
            try:
                weights[name.strip()] = float(w)
            except ValueError:
                continue
        burst_env = os.environ.get("RT_SERVE_TENANT_BURST", "")
        return cls(
            rate=rate,
            burst=float(burst_env) if burst_env else None,
            weights=weights,
            max_queued=int(os.environ.get(
                "RT_SERVE_TENANT_MAX_QUEUED", "128")))

    def weight(self, tenant: str) -> float:
        w = self.weights.get(tenant, self.default_weight)
        return w if w > 0 else self.default_weight

    # The tenant key is CLIENT-SUPPLIED (x-tenant header), so per-tenant
    # state must not grow without bound under unique-key abuse: past
    # this size, admit() opportunistically drops entries idle long
    # enough that rebuilding them is lossless (a full bucket refills to
    # full; an idle finish tag re-enters at the virtual clock anyway).
    PRUNE_ABOVE = 1024
    PRUNE_IDLE_S = 60.0

    def _maybe_prune(self, now: float):
        if len(self._buckets) > self.PRUNE_ABOVE:
            # Prune only entries whose TRUE refill has already reached
            # full burst — recreating those at full is lossless.  An
            # idle-but-still-refilling bucket (low rate, high burst)
            # must be kept, or eviction would hand the tenant its full
            # burst back early.
            self._buckets = {
                t: b for t, b in self._buckets.items()
                if now - b.last < self.PRUNE_IDLE_S
                or b.tokens + (now - b.last) * self.rate < self.burst}
        if len(self._finish) > self.PRUNE_ABOVE:
            self._finish = {t: f for t, f in self._finish.items()
                            if f > self._vclock}

    # ------------------------------------------------------- admission
    def admit(self, deployment: str, tenant: str, queued_now: int):
        """Gate one request at the router's edge; raises
        :class:`TenantThrottled` (after counting the shed) instead of
        letting an over-budget tenant join the line."""
        self._maybe_prune(time.monotonic())
        if queued_now >= self.max_queued:
            self._shed(deployment, tenant, "queue_full")
            raise TenantThrottled(
                f"tenant {tenant!r} has {queued_now} requests waiting "
                f"(cap {self.max_queued}); shedding instead of queueing",
                tenant=tenant, reason="queue_full",
                retry_after_s=1.0)
        if self.rate <= 0:
            return
        now = time.monotonic()
        b = self._buckets.get(tenant)
        if b is None:
            b = self._buckets[tenant] = _Bucket(self.burst, now)
        b.tokens = min(self.burst, b.tokens + (now - b.last) * self.rate)
        b.last = now
        if b.tokens >= 1.0:
            b.tokens -= 1.0
            return
        retry = (1.0 - b.tokens) / self.rate
        self._shed(deployment, tenant, "rate_limited")
        raise TenantThrottled(
            f"tenant {tenant!r} over its {self.rate:g} req/s budget "
            f"(burst {self.burst:g}); retry in {retry:.2f}s",
            tenant=tenant, reason="rate_limited",
            retry_after_s=retry)

    def _shed(self, deployment: str, tenant: str, reason: str):
        self.shed_total += 1
        TENANT_SHED_COUNTER.inc(tags={"deployment": deployment,
                                      "tenant": tenant,
                                      "reason": reason})

    # ---------------------------------------------- weighted fairness
    def start_tag(self, tenant: str) -> float:
        """Finish tag for a newly queued waiter: tenants are serviced
        in ascending tag order, and a tenant's tags advance 1/weight
        per request — the start-time fair queueing discipline."""
        f = max(self._vclock, self._finish.get(tenant, 0.0)) \
            + 1.0 / self.weight(tenant)
        self._finish[tenant] = f
        return f

    def dispatched(self, tag: float):
        """Advance the virtual clock past the dispatched waiter's tag
        (idle tenants re-enter at the current clock, not at zero, so
        sleeping does not bank unbounded credit)."""
        if tag > self._vclock:
            self._vclock = tag
