"""Long-poll config propagation: the controller hosts versioned snapshots;
routers/proxies/handles block on `listen` and wake only when a watched key
changes.

Reference: python/ray/serve/_private/long_poll.py — LongPollHost (:179)
with snapshot_ids + asyncio events, LongPollClient (:63) re-issuing
listen calls in a loop.  Identical shape here, riding our actor RPC plane
instead of Ray's.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Dict, Optional, Tuple

LISTEN_TIMEOUT_S = 30.0


class LongPollHost:
    """Lives inside the controller actor.  Keys map to (snapshot_id,
    object); listeners block until any of their keys moves past the
    snapshot id they already have."""

    def __init__(self):
        self._snapshot_ids: Dict[str, int] = {}
        self._objects: Dict[str, Any] = {}
        self._events: Dict[str, asyncio.Event] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    def notify_changed(self, key: str, obj: Any) -> None:
        """Thread-safe: often called from controller executor threads while
        listeners wait on the actor's event loop."""
        self._snapshot_ids[key] = self._snapshot_ids.get(key, -1) + 1
        self._objects[key] = obj
        ev = self._events.pop(key, None)
        if ev is not None and self._loop is not None:
            self._loop.call_soon_threadsafe(ev.set)

    async def listen(self, keys_to_snapshot_ids: Dict[str, int]) -> Dict:
        """Return {key: (snapshot_id, object)} for every watched key that
        is newer than what the caller has; block (bounded) if none are."""
        self._loop = asyncio.get_running_loop()
        while True:
            # Register events BEFORE the snapshot check: a notify from an
            # executor thread between check and registration would
            # otherwise be lost, stalling this listener for the full
            # timeout while it holds stale routing state.
            waiters = []
            events = []
            for k in keys_to_snapshot_ids:
                ev = self._events.get(k)
                if ev is None:
                    ev = self._events[k] = asyncio.Event()
                events.append(ev)
            updated = {
                k: (self._snapshot_ids[k], self._objects[k])
                for k, sid in keys_to_snapshot_ids.items()
                if self._snapshot_ids.get(k, -1) > sid
            }
            if updated:
                return updated
            waiters = [asyncio.ensure_future(ev.wait()) for ev in events]
            done, pending = await asyncio.wait(
                waiters, timeout=LISTEN_TIMEOUT_S,
                return_when=asyncio.FIRST_COMPLETED)
            for p in pending:
                p.cancel()
            if not done:
                return {}  # bounded poll: client re-issues


class LongPollClient:
    """Async-side client: loops `listen` against the controller actor and
    invokes callbacks on updates (reference: long_poll.py:63)."""

    def __init__(self, controller_handle, key_listeners: Dict[str, Callable],
                 loop: Optional[asyncio.AbstractEventLoop] = None):
        self._controller = controller_handle
        self._listeners = dict(key_listeners)
        self._snapshot_ids = {k: -1 for k in self._listeners}
        self._stopped = False
        self._task = (loop or asyncio.get_event_loop()).create_task(
            self._run())

    async def _run(self):
        while not self._stopped:
            try:
                ref = self._controller.listen_for_change.remote(
                    dict(self._snapshot_ids))
                # wrap_future: safe on any loop (see router.assign_replica).
                updates = await asyncio.wrap_future(ref.future())
            except Exception:
                if self._stopped:
                    return
                await asyncio.sleep(0.5)
                continue
            for key, (sid, obj) in (updates or {}).items():
                self._snapshot_ids[key] = sid
                cb = self._listeners.get(key)
                if cb is not None:
                    cb(obj)

    def stop(self):
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
