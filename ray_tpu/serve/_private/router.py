"""Router: assigns queries to replicas, honoring max_concurrent_queries.

Reference: python/ray/serve/_private/router.py — Router (:262) +
ReplicaSet.assign_replica (:222): pick a replica with a free slot
(in-flight < max_concurrent_queries); if all are saturated, queue the
query until one frees.  Replica membership arrives via long poll.

Saturation is observable: queue depth and in-flight counts are exported
as util.metrics gauges (serve_router_queue_depth / serve_router_in_flight
/ serve_replica_in_flight) so a saturated deployment shows up next to
the engine metrics instead of manifesting only as latency.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, AsyncIterator, Dict, List, Optional

from ray_tpu.serve._private.long_poll import LongPollClient
from ray_tpu.util import metrics as _metrics

logger = logging.getLogger(__name__)

_worker_mod = None


def _core_worker():
    """The process's CoreWorker, with the module resolved once (lazy to
    dodge import cycles, cached to keep it off the per-request path)."""
    global _worker_mod
    if _worker_mod is None:
        from ray_tpu._private import worker as worker_mod
        _worker_mod = worker_mod
    return _worker_mod.global_worker

QUEUE_DEPTH_GAUGE = _metrics.Gauge(
    "serve_router_queue_depth",
    "Queries waiting in this process's router for a free replica slot",
    tag_keys=("deployment",))
IN_FLIGHT_GAUGE = _metrics.Gauge(
    "serve_router_in_flight",
    "Queries this process's router has in flight across all replicas",
    tag_keys=("deployment",))
REPLICA_IN_FLIGHT_GAUGE = _metrics.Gauge(
    "serve_replica_in_flight",
    "Queries this process's router has in flight per replica",
    tag_keys=("deployment", "replica"))


class _UnaryResult:
    """Wrapper yielded (once) by assign_replica_stream(unary_fallback=
    True) when the target turned out not to stream: the deployment ran
    exactly once and this is its whole answer — the proxy formats it as
    a plain HTTP response instead of SSE."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


class ReplicaSet:
    """The live replicas of one deployment, with in-flight accounting.

    Hot-path detail: the saturation gauges are written through
    pre-resolved series handles (`Metric.series`) — one dict store per
    update instead of a tag merge + lock per call — and the unary call
    path resolves replica replies via the CoreWorker's ready-future
    fast path (no per-call coroutine on the IO loop, reply deserialized
    on this router's own thread)."""

    def __init__(self, deployment_name: str, loop):
        self.deployment_name = deployment_name
        self._loop = loop
        self._replicas: List[Dict] = []
        self._in_flight: Dict[str, int] = {}
        self._slot_freed = asyncio.Event()
        self.num_queued = 0
        self._g_queued = QUEUE_DEPTH_GAUGE.series(
            {"deployment": deployment_name})
        self._g_in_flight = IN_FLIGHT_GAUGE.series(
            {"deployment": deployment_name})
        self._g_replica: Dict[str, object] = {}
        self._num_in_flight = 0

    def _replica_series(self, tag: str):
        s = self._g_replica.get(tag)
        if s is None:
            s = self._g_replica[tag] = REPLICA_IN_FLIGHT_GAUGE.series(
                {"deployment": self.deployment_name, "replica": tag})
        return s

    def update_replicas(self, infos: List[Dict]):
        self._replicas = list(infos)
        tags = {i["replica_tag"] for i in infos}
        for gone in set(self._in_flight) - tags:
            # Zero the departed replica's series: its finally-block
            # decrement is skipped once the tag is dropped, and a
            # stale nonzero gauge would misreport saturation forever.
            self._replica_series(gone).set(0)
            self._g_replica.pop(gone, None)
        self._in_flight = {t: self._in_flight.get(t, 0) for t in tags}
        self._num_in_flight = sum(self._in_flight.values())
        self._g_in_flight.set(self._num_in_flight)
        self._slot_freed.set()  # membership change may free capacity

    def _set_queued(self, delta: int):
        self.num_queued += delta
        self._g_queued.set(self.num_queued)

    def _track_in_flight(self, tag: str, delta: int):
        n = self._in_flight[tag] = self._in_flight.get(tag, 0) + delta
        self._num_in_flight += delta
        self._g_in_flight.set(self._num_in_flight)
        self._replica_series(tag).set(n)

    async def _acquire(self, timeout_s: float) -> Dict:
        """Wait (bounded) for a replica with a free slot; the caller owns
        one in-flight unit on the returned replica and must release it
        via _track_in_flight(tag, -1)."""
        import time as _time
        deadline = _time.monotonic() + timeout_s
        self._set_queued(+1)
        try:
            while True:
                choice = self._pick()
                if choice is not None:
                    break
                remain = deadline - _time.monotonic()
                if remain <= 0:
                    raise RuntimeError(
                        f"no available replica for deployment "
                        f"{self.deployment_name!r} within {timeout_s}s")
                self._slot_freed.clear()
                try:
                    await asyncio.wait_for(self._slot_freed.wait(),
                                           timeout=min(remain, 5.0))
                except asyncio.TimeoutError:
                    pass  # re-check membership; maybe replicas arrived
        finally:
            self._set_queued(-1)
        self._track_in_flight(choice["replica_tag"], +1)
        return choice

    async def assign_replica(self, method_name: str, args: tuple,
                             kwargs: dict,
                             timeout_s: float = 120.0) -> Any:
        """Pick a replica (power-of-two-choices among free ones), send the
        query, and release the slot when it completes.  Bounded: a request
        that can't be assigned within timeout_s (no replicas — deployment
        deleted or all crashed) errors instead of hanging forever."""
        choice = await self._acquire(timeout_s)
        tag = choice["replica_tag"]
        try:
            actor = choice["actor"]
            ref = actor.handle_request.remote(method_name, args, kwargs)
            # Fast path: wait on the owned entry's ready-future (fired
            # straight from the reply handler — no per-call coroutine on
            # the CoreWorker loop) and deserialize HERE, on the router's
            # thread.  In-store/borrowed replies fall back to the full
            # get() path, which also rides the IO loop safely from any
            # thread (the router often runs on its own loop).
            w = _core_worker()
            ready_future = getattr(w, "ready_future", None)
            if ready_future is None:  # e.g. local-mode worker
                return await asyncio.wrap_future(ref.future())
            fut = ready_future(ref)
            if not fut.done():
                await asyncio.wrap_future(fut)
            ok, value = w.try_take_local_value(ref)
            if ok:
                return value
            return await asyncio.wrap_future(ref.future())
        finally:
            if tag in self._in_flight:
                self._track_in_flight(tag, -1)
            self._slot_freed.set()

    async def assign_replica_stream(self, method_name: str, args: tuple,
                                    kwargs: dict,
                                    timeout_s: float = 120.0,
                                    unary_fallback: bool = False
                                    ) -> AsyncIterator:
        """Streaming twin of assign_replica: starts a generator-valued
        call on one replica and returns an async iterator over its
        items.  The replica's in-flight slot is held for the LIFETIME of
        the stream (a generating request occupies engine capacity, so it
        must count against max_concurrent_queries the whole time);
        closing the iterator early cancels the remote stream.

        A target that turns out NOT to stream ran exactly once on the
        replica; with unary_fallback the iterator yields its value
        wrapped in _UnaryResult (proxy path — degrade to a plain
        response), otherwise it raises TypeError (handle.stream() on a
        unary method is caller error)."""

        async def _gen():
            # Everything — INCLUDING slot acquisition — happens inside
            # the generator body: a stream that is closed (or dropped)
            # before its first iteration never starts this body, and an
            # unstarted generator's finally never runs, so acquiring
            # out here would leak the in-flight slot forever.
            choice = await self._acquire(timeout_s)
            tag = choice["replica_tag"]
            actor = choice["actor"]
            finished = False
            stream_id = None
            try:
                started = await asyncio.wrap_future(
                    actor.handle_request_streaming.remote(
                        method_name, args, kwargs).future())
                if "stream_id" not in started:
                    finished = True
                    if not unary_fallback:
                        raise TypeError(
                            f"{self.deployment_name}."
                            f"{method_name or '__call__'} returned a "
                            "non-streaming result; use handle.remote() "
                            "for unary calls")
                    yield _UnaryResult(started["unary"])
                    return
                stream_id = started["stream_id"]
                cursor = 0
                while True:
                    out = await asyncio.wrap_future(
                        actor.stream_next.remote(stream_id,
                                                 cursor).future())
                    for item in out["items"]:
                        yield item
                    cursor += len(out["items"])
                    if out["done"]:
                        finished = True
                        if out.get("error") is not None:
                            raise out["error"]
                        return
            finally:
                if stream_id is not None and not finished:
                    # Early close / client gone: free the replica-side
                    # stream (and whatever slot it holds in an engine).
                    actor.stream_cancel.options(num_returns=0).remote(
                        stream_id)
                if tag in self._in_flight:
                    self._track_in_flight(tag, -1)
                self._slot_freed.set()

        return _gen()

    def _pick(self) -> Optional[Dict]:
        free = [r for r in self._replicas
                if self._in_flight.get(r["replica_tag"], 0)
                < r["max_concurrent_queries"]]
        if not free:
            return None
        if len(free) == 1:
            return free[0]
        # Power of two choices: least-loaded of two random candidates.
        a, b = random.sample(free, 2)
        return a if (self._in_flight.get(a["replica_tag"], 0)
                     <= self._in_flight.get(b["replica_tag"], 0)) else b

    def stats(self) -> Dict:
        return {"queued": self.num_queued,
                "in_flight": sum(self._in_flight.values()),
                "num_replicas": len(self._replicas)}


class Router:
    """One per handle-holding process (proxy, driver, or other actor)."""

    def __init__(self, controller_handle, deployment_name: str,
                 loop: Optional[asyncio.AbstractEventLoop] = None):
        loop = loop or asyncio.get_event_loop()
        self.deployment_name = deployment_name
        self.replica_set = ReplicaSet(deployment_name, loop)
        self._long_poll = LongPollClient(
            controller_handle,
            {f"replicas::{deployment_name}":
                self.replica_set.update_replicas},
            loop=loop)

    async def assign_request(self, method_name: str, args: tuple,
                             kwargs: dict):
        return await self.replica_set.assign_replica(
            method_name, args, kwargs)

    async def assign_request_stream(self, method_name: str, args: tuple,
                                    kwargs: dict):
        return await self.replica_set.assign_replica_stream(
            method_name, args, kwargs)

    def stop(self):
        self._long_poll.stop()
