"""Router: assigns queries to replicas, honoring max_concurrent_queries.

Reference: python/ray/serve/_private/router.py — Router (:262) +
ReplicaSet.assign_replica (:222): pick a replica with a free slot
(in-flight < max_concurrent_queries); if all are saturated, queue the
query until one frees.  Replica membership arrives via long poll.

Robustness layer (the multi-replica serving contract):

  * STREAM FAILOVER — every stream records resumable state (deployment,
    args, items delivered).  When the serving replica dies mid-stream
    the router re-submits on a healthy replica: resumable deployments
    (serve.resumable) get the delivered prefix passed back so only the
    REMAINING items are produced (greedy parity preserved; the prefix
    cache makes re-prefill cheap), non-resumable streams restart only
    if zero items were delivered.  Anything else fails fast with a
    structured StreamInterrupted carrying a resume cursor — never a
    silent hang (every stream RPC is deadline-bounded).
  * UNARY RETRY — a replica that dies before its first response is
    retried once on a DIFFERENT replica (zero bytes were delivered, so
    the retry is prefix-safe) instead of surfacing a raw
    ActorDiedError.
  * PER-TENANT QoS — with a TenantQoS policy installed, admission runs
    a per-tenant token bucket + queue cap (overload sheds with
    TenantThrottled → HTTP 429) and saturated-capacity waiting is
    weighted-fair across tenants instead of a free-for-all.

Saturation is observable: queue depth and in-flight counts are exported
as util.metrics gauges (serve_router_queue_depth / serve_router_in_flight
/ serve_replica_in_flight) so a saturated deployment shows up next to
the engine metrics instead of manifesting only as latency; failovers and
interruptions count in serve_stream_failovers_total /
serve_stream_interrupted_total.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import random
import time
from typing import Any, AsyncIterator, Deque, Dict, List, Optional

from ray_tpu._private import failpoints
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import GLOBAL_CONFIG as _cfg
from ray_tpu.serve._private.long_poll import LongPollClient
from ray_tpu.serve._private.qos import DEFAULT_TENANT, TenantQoS
from ray_tpu.serve.exceptions import StreamInterrupted
from ray_tpu.util import metrics as _metrics

logger = logging.getLogger(__name__)

_worker_mod = None
_death_errs = None


def _core_worker():
    """The process's CoreWorker, with the module resolved once (lazy to
    dodge import cycles, cached to keep it off the per-request path)."""
    global _worker_mod
    if _worker_mod is None:
        from ray_tpu._private import worker as worker_mod
        _worker_mod = worker_mod
    return _worker_mod.global_worker


def _death_errors() -> tuple:
    """Exception types that mean THE REPLICA is gone (vs the request
    failing inside healthy user code): actor death/unavailability and
    transport loss.  Resolved lazily to dodge import cycles."""
    global _death_errs
    if _death_errs is None:
        from ray_tpu import exceptions as rexc
        from ray_tpu._private import protocol
        _death_errs = (rexc.ActorDiedError, rexc.ActorUnavailableError,
                       protocol.ConnectionLost)
    return _death_errs


QUEUE_DEPTH_GAUGE = _metrics.Gauge(
    "serve_router_queue_depth",
    "Queries waiting in this process's router for a free replica slot",
    tag_keys=("deployment",))
IN_FLIGHT_GAUGE = _metrics.Gauge(
    "serve_router_in_flight",
    "Queries this process's router has in flight across all replicas",
    tag_keys=("deployment",))
REPLICA_IN_FLIGHT_GAUGE = _metrics.Gauge(
    "serve_replica_in_flight",
    "Queries this process's router has in flight per replica",
    tag_keys=("deployment", "replica"))
FAILOVER_COUNTER = _metrics.Counter(
    "serve_stream_failovers_total",
    "Streams re-submitted on a healthy replica after their replica died",
    tag_keys=("deployment",))
INTERRUPTED_COUNTER = _metrics.Counter(
    "serve_stream_interrupted_total",
    "Streams that failed structured (StreamInterrupted) after replica "
    "death with failover unavailable",
    tag_keys=("deployment",))
UNARY_RETRY_COUNTER = _metrics.Counter(
    "serve_unary_retries_total",
    "Unary calls retried on a different replica after actor death "
    "before first response",
    tag_keys=("deployment",))
AFFINITY_HITS_COUNTER = _metrics.Counter(
    "serve_kv_affinity_hits_total",
    "Assignments routed to a replica already holding a prefix of the "
    "request (prefix-affinity override of the load-based pick)",
    tag_keys=("deployment",))
AFFINITY_SCORE_GAUGE = _metrics.Gauge(
    "serve_router_affinity_score",
    "Blended affinity score of the last affinity-scored assignment "
    "(blend * hit-depth - (1-blend) * load; negative = load dominated)",
    tag_keys=("deployment",))

_QOS_FROM_ENV = "__env__"


class _UnaryResult:
    """Wrapper yielded (once) by assign_replica_stream(unary_fallback=
    True) when the target turned out not to stream: the deployment ran
    exactly once and this is its whole answer — the proxy formats it as
    a plain HTTP response instead of SSE."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


class _Waiter:
    """One queued acquisition under QoS: resolved with the chosen
    replica info dict by the WFQ dispatcher."""

    __slots__ = ("fut", "tenant", "exclude", "tag", "hint")

    def __init__(self, fut, tenant: str, exclude: tuple, tag: float,
                 hint: Optional[Dict] = None):
        self.fut = fut
        self.tenant = tenant
        self.exclude = exclude
        self.tag = tag
        self.hint = hint


class ReplicaSet:
    """The live replicas of one deployment, with in-flight accounting.

    Hot-path detail: the saturation gauges are written through
    pre-resolved series handles (`Metric.series`) — one dict store per
    update instead of a tag merge + lock per call — and the unary call
    path resolves replica replies via the CoreWorker's ready-future
    fast path (no per-call coroutine on the IO loop, reply deserialized
    on this router's own thread)."""

    def __init__(self, deployment_name: str, loop,
                 qos: Any = _QOS_FROM_ENV):
        self.deployment_name = deployment_name
        self._loop = loop
        self._replicas: List[Dict] = []
        self._in_flight: Dict[str, int] = {}
        self._slot_freed = asyncio.Event()
        self.num_queued = 0
        self._g_queued = QUEUE_DEPTH_GAUGE.series(
            {"deployment": deployment_name})
        self._g_in_flight = IN_FLIGHT_GAUGE.series(
            {"deployment": deployment_name})
        self._g_replica: Dict[str, object] = {}
        self._num_in_flight = 0
        self._qos: Optional[TenantQoS] = (
            TenantQoS.from_env() if qos is _QOS_FROM_ENV else qos)
        self._waiters: Dict[str, Deque[_Waiter]] = {}
        env = os.environ.get
        self._stream_failover = env("RT_SERVE_STREAM_FAILOVER",
                                    "1") != "0"
        self._max_failovers = int(env("RT_SERVE_STREAM_MAX_FAILOVERS",
                                      "2"))
        self._unary_retry = env("RT_SERVE_UNARY_RETRY", "1") != "0"
        self._stream_poll_timeout = float(
            env("RT_SERVE_STREAM_POLL_TIMEOUT_S", "60"))
        self._suppress_ttl = float(
            env("RT_SERVE_REPLICA_SUPPRESS_S", "10"))
        self._suppressed: Dict[str, float] = {}
        # KV pull addresses this router has OBSERVED in the membership
        # broadcast: current members, plus recently-departed ones kept
        # for a grace window (a dead replica leaves the broadcast
        # before its client's resume retry arrives).  Client-replayed
        # kv_origin cursors are validated against these — see
        # _trusted_rdv.
        self._member_rdv: set = set()
        self._recent_rdv: Dict[tuple, float] = {}

    def _replica_series(self, tag: str):
        s = self._g_replica.get(tag)
        if s is None:
            s = self._g_replica[tag] = REPLICA_IN_FLIGHT_GAUGE.series(
                {"deployment": self.deployment_name, "replica": tag})
        return s

    @staticmethod
    def _rdv_key(rdv) -> Optional[tuple]:
        """Canonical (host, port, engine) key of a kv_rdv dict, or None
        when it isn't one (missing fields, junk types)."""
        try:
            return (str(rdv["host"]), int(rdv["port"]),
                    str(rdv.get("engine", "default")))
        except (TypeError, KeyError, ValueError):
            return None

    def _trusted_rdv(self, rdv) -> Optional[Dict]:
        """Validate a CLIENT-supplied kv_origin (x-rt-resume rides in
        from the open HTTP surface): only pull addresses this router has
        itself seen in the controller's membership broadcast — live now,
        or departed within serve_kv_rdv_grace_s — are honored, and the
        returned dict is rebuilt from the canonical key (no smuggled
        fields).  Anything else is dropped: a forged cursor must not be
        able to point a replica's migration pull at an attacker-chosen
        endpoint (SSRF) or seed the shared prefix cache from bytes an
        attacker serves (cache poisoning).  Dropping is safe — the
        resume simply re-prefills."""
        key = self._rdv_key(rdv) if isinstance(rdv, dict) else None
        if key is None:
            return None
        if key in self._member_rdv or \
                self._recent_rdv.get(key, 0.0) > time.monotonic():
            return {"host": key[0], "port": key[1], "engine": key[2]}
        logger.warning(
            "dropping kv_origin %s:%s from resume cursor: not a pull "
            "address this router observed in %s's membership",
            rdv.get("host"), rdv.get("port"), self.deployment_name)
        return None

    def update_replicas(self, infos: List[Dict]):
        self._replicas = list(infos)
        now = time.monotonic()
        member = set()
        for i in infos:
            key = self._rdv_key(i.get("kv_rdv"))
            if key is not None:
                member.add(key)
        for gone in self._member_rdv - member:
            self._recent_rdv[gone] = now + _cfg.serve_kv_rdv_grace_s
        for key, deadline in list(self._recent_rdv.items()):
            if deadline <= now or key in member:
                del self._recent_rdv[key]
        self._member_rdv = member
        tags = {i["replica_tag"] for i in infos}
        for gone in set(self._in_flight) - tags:
            # Zero the departed replica's series: its finally-block
            # decrement is skipped once the tag is dropped, and a
            # stale nonzero gauge would misreport saturation forever.
            self._replica_series(gone).set(0)
            self._g_replica.pop(gone, None)
        self._in_flight = {t: self._in_flight.get(t, 0) for t in tags}
        self._num_in_flight = sum(self._in_flight.values())
        self._g_in_flight.set(self._num_in_flight)
        self._slot_freed.set()  # membership change may free capacity
        self._dispatch_waiters()

    def _drop_replica(self, tag: str):
        """Suppress a replica the router just observed dying so no new
        work lands on it during the window before the controller's
        membership broadcast confirms the death.  Suppression is a
        bounded TTL, not removal: the long-poll only re-delivers
        membership when the controller's fingerprint CHANGES, so
        removing a replica the controller still considers RUNNING
        (death mis-classified — a transient stall or injected fault)
        would shrink this router's capacity forever.  A really-dead
        replica leaves the broadcast within the health-check period,
        well inside the TTL renewal from its next failed call."""
        self._suppressed[tag] = \
            asyncio.get_event_loop().time() + self._suppress_ttl
        logger.warning(
            "replica %s of %s suppressed in local view for %.0fs "
            "(died mid-call); awaiting controller broadcast",
            tag, self.deployment_name, self._suppress_ttl)

    def _set_queued(self, delta: int):
        self.num_queued += delta
        self._g_queued.set(self.num_queued)

    def _track_in_flight(self, tag: str, delta: int):
        n = self._in_flight[tag] = self._in_flight.get(tag, 0) + delta
        self._num_in_flight += delta
        self._g_in_flight.set(self._num_in_flight)
        self._replica_series(tag).set(n)

    def _release(self, tag: str):
        """Give back one in-flight unit and wake whoever is waiting for
        capacity (the legacy event loop AND the QoS dispatcher).
        Floor at zero: a replica that left and re-entered the broadcast
        (drain -> un-drain) had its count reset while old streams still
        held slots; their releases must not drive the count negative
        and mint phantom capacity forever."""
        if self._in_flight.get(tag, 0) > 0:
            self._track_in_flight(tag, -1)
        self._slot_freed.set()
        self._dispatch_waiters()

    # -------------------------------------------------- slot acquisition
    async def _acquire(self, timeout_s: float, tenant: str = None,
                       exclude: tuple = (), admit: bool = True,
                       hint: Optional[Dict] = None) -> Dict:
        """Wait (bounded) for a replica with a free slot; the caller owns
        one in-flight unit on the returned replica and must release it
        via _release(tag).  With a QoS policy installed, admission runs
        the per-tenant token bucket + queue cap and waiting is
        weighted-fair across tenants.  `admit=False` skips the
        admission gate (WFQ ordering still applies): retries and
        failovers of an ALREADY-ADMITTED request must neither burn a
        second bucket token nor convert a replica death into a 429."""
        t0 = time.time()
        if self._qos is not None:
            choice = await self._acquire_qos(timeout_s, tenant, exclude,
                                             admit, hint)
            self._record_wait(t0, time.time(), tenant, choice)
            return choice
        deadline = time.monotonic() + timeout_s
        self._set_queued(+1)
        try:
            while True:
                choice = self._pick(exclude, hint)
                if choice is not None:
                    break
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise RuntimeError(
                        f"no available replica for deployment "
                        f"{self.deployment_name!r} within {timeout_s}s")
                self._slot_freed.clear()
                try:
                    await asyncio.wait_for(self._slot_freed.wait(),
                                           timeout=min(remain, 5.0))
                except asyncio.TimeoutError:
                    pass  # re-check membership; maybe replicas arrived
        finally:
            self._set_queued(-1)
        self._track_in_flight(choice["replica_tag"], +1)
        self._record_wait(t0, time.time(), tenant, choice)
        return choice

    def _record_wait(self, t0: float, t1: float, tenant, choice):
        """serve.qos_wait span: time a request spent waiting for a
        replica slot (QoS admission + WFQ, or the legacy capacity
        wait).  Linked under the caller's span (the proxy's
        serve.request or a handle caller's context)."""
        _tracing.record("serve", "serve.qos_wait", t0, t1 - t0,
                        trace=_tracing.child_span(),
                        args={"deployment": self.deployment_name,
                              "tenant": tenant or "default",
                              "replica": choice["replica_tag"]})

    async def _acquire_qos(self, timeout_s: float, tenant: str,
                           exclude: tuple, admit: bool = True,
                           hint: Optional[Dict] = None) -> Dict:
        tenant = tenant or DEFAULT_TENANT
        dq = self._waiters.get(tenant)
        if dq:
            while dq and dq[0].fut.done():
                dq.popleft()
        if admit:
            # Count only LIVE waiters toward the cap: a timed-out/
            # cancelled waiter stranded mid-deque (behind a live head)
            # must not shed new requests with a phantom queue_full.
            queued_now = sum(1 for x in dq
                             if not x.fut.done()) if dq else 0
            self._qos.admit(self.deployment_name, tenant, queued_now)
        loop = asyncio.get_running_loop()
        w = _Waiter(loop.create_future(), tenant, tuple(exclude or ()),
                    self._qos.start_tag(tenant), hint)
        self._waiters.setdefault(
            tenant, collections.deque()).append(w)
        self._set_queued(+1)
        loop_time = loop.time
        deadline = loop_time() + timeout_s
        try:
            self._dispatch_waiters()
            while True:
                remain = deadline - loop_time()
                if remain <= 0:
                    self._abandon_waiter(w)
                    raise RuntimeError(
                        f"no available replica for deployment "
                        f"{self.deployment_name!r} within {timeout_s}s")
                try:
                    # Shielded sub-waits (<=5s): the periodic wake
                    # re-runs the dispatcher because capacity can
                    # reappear WITHOUT any release/broadcast event —
                    # e.g. a replica's death-suppression TTL expiring.
                    return await asyncio.wait_for(
                        asyncio.shield(w.fut), min(remain, 5.0))
                except asyncio.TimeoutError:
                    self._dispatch_waiters()
                except BaseException:
                    # Caller cancelled / generator closed (GeneratorExit
                    # reaches here too) — propagate, but never leave a
                    # live waiter behind for the dispatcher to hand a
                    # slot nobody will consume, and never leak a slot
                    # assigned in the race.
                    self._abandon_waiter(w)
                    raise
        finally:
            self._set_queued(-1)

    def _abandon_waiter(self, w: "_Waiter"):
        """A waiter whose wait died (deadline, cancellation, generator
        close) may ALREADY have been handed a slot by the dispatcher in
        the same loop tick — hand it straight back instead of leaking
        it against max_concurrent_queries forever.  A still-pending
        waiter is cancelled so the dispatcher prunes it instead of
        assigning a slot nobody will consume."""
        if w.fut.done() and not w.fut.cancelled() \
                and w.fut.exception() is None:
            self._release(w.fut.result()["replica_tag"])
        elif not w.fut.done():
            w.fut.cancel()

    def _dispatch_waiters(self):
        """Match queued waiters to free replica slots in WFQ order
        (smallest virtual finish tag first).  Runs on the router loop
        whenever capacity may have appeared."""
        if self._qos is None or not self._waiters:
            return
        while True:
            heads: List[_Waiter] = []
            for tenant in list(self._waiters):
                dq = self._waiters[tenant]
                while dq and dq[0].fut.done():
                    dq.popleft()
                if not dq:
                    del self._waiters[tenant]
                else:
                    heads.append(dq[0])
            if not heads:
                return
            heads.sort(key=lambda x: x.tag)
            placed = False
            for w in heads:
                choice = self._pick(w.exclude, w.hint)
                if choice is None:
                    continue  # only excluded replicas free; try others
                dq = self._waiters.get(w.tenant)
                dq.popleft()
                if not dq:
                    del self._waiters[w.tenant]
                self._qos.dispatched(w.tag)
                self._track_in_flight(choice["replica_tag"], +1)
                w.fut.set_result(choice)
                placed = True
                break
            if not placed:
                return

    # ------------------------------------------------------- stream RPCs
    async def _stream_rpc(self, ref):
        """Await one streaming-transport RPC with a bounded deadline:
        a reply that outlives the bound (replica wedged behind a
        partition the keepalive hasn't condemned yet) is classified as
        replica unavailability, so the stream fails over or interrupts
        structured instead of hanging."""
        fut = asyncio.wrap_future(ref.future())
        if self._stream_poll_timeout <= 0:
            return await fut
        try:
            return await asyncio.wait_for(fut,
                                          self._stream_poll_timeout)
        except asyncio.TimeoutError:
            from ray_tpu import exceptions as rexc
            raise rexc.ActorUnavailableError(
                None, f"stream RPC gave no reply within "
                      f"{self._stream_poll_timeout}s") from None

    @staticmethod
    def _check_stream_failpoint():
        """`serve.stream_next` failpoint: deterministic chaos on the
        router→replica streaming leg (delay = slow link; error/
        disconnect = transport loss, which exercises the failover
        path)."""
        if not failpoints.ACTIVE:
            return None
        act = failpoints.check("serve.stream_next")
        if act is None:
            return None
        if act.kind == "delay":
            return act.delay_s
        from ray_tpu import exceptions as rexc
        raise rexc.ActorUnavailableError(
            None, f"failpoint: injected stream_next {act.kind}")

    async def assign_replica(self, method_name: str, args: tuple,
                             kwargs: dict,
                             timeout_s: float = 120.0,
                             tenant: str = None,
                             affinity: Optional[Dict] = None) -> Any:
        """Pick a replica (power-of-two-choices among free ones), send the
        query, and release the slot when it completes.  Bounded: a request
        that can't be assigned within timeout_s (no replicas — deployment
        deleted or all crashed) errors instead of hanging forever.  A
        replica that dies before its first response is retried ONCE on a
        different replica (zero bytes were delivered, so re-running is
        prefix-safe) instead of leaking a raw ActorDiedError.  NB this
        makes unary serve calls at-least-once across replica death —
        the replica may have executed before the connection died (same
        trade the task layer makes across restarts); deployments with
        non-idempotent side effects can opt out via
        RT_SERVE_UNARY_RETRY=0."""
        exclude: tuple = ()
        attempt = 0
        while True:
            choice = await self._acquire(timeout_s, tenant=tenant,
                                         exclude=exclude,
                                         admit=attempt == 0,
                                         hint=affinity)
            tag = choice["replica_tag"]
            span_args = {"deployment": self.deployment_name,
                         "replica": tag, "attempt": attempt}
            if choice.get("_affinity"):
                span_args["affinity"] = choice["_affinity"]
            try:
                try:
                    with _tracing.span(
                            "serve", "serve.assign", args=span_args):
                        return await self._call_unary(
                            choice, method_name, args, kwargs)
                except _death_errors() as e:
                    self._drop_replica(tag)
                    if attempt == 0 and self._unary_retry:
                        attempt = 1
                        exclude = (tag,)
                        UNARY_RETRY_COUNTER.inc(
                            tags={"deployment": self.deployment_name})
                        logger.warning(
                            "replica %s died before replying to %s.%s; "
                            "retrying once on a different replica (%s)",
                            tag, self.deployment_name,
                            method_name or "__call__", e)
                        continue
                    raise
            finally:
                self._release(tag)

    async def _call_unary(self, choice: Dict, method_name: str,
                          args: tuple, kwargs: dict) -> Any:
        actor = choice["actor"]
        ref = actor.handle_request.remote(method_name, args, kwargs)
        # Fast path: wait on the owned entry's ready-future (fired
        # straight from the reply handler — no per-call coroutine on
        # the CoreWorker loop) and deserialize HERE, on the router's
        # thread.  In-store/borrowed replies fall back to the full
        # get() path, which also rides the IO loop safely from any
        # thread (the router often runs on its own loop).
        w = _core_worker()
        ready_future = getattr(w, "ready_future", None)
        if ready_future is None:  # e.g. local-mode worker
            return await asyncio.wrap_future(ref.future())
        fut = ready_future(ref)
        if not fut.done():
            await asyncio.wrap_future(fut)
        ok, value = w.try_take_local_value(ref)
        if ok:
            return value
        return await asyncio.wrap_future(ref.future())

    async def assign_replica_stream(self, method_name: str, args: tuple,
                                    kwargs: dict,
                                    timeout_s: float = 120.0,
                                    unary_fallback: bool = False,
                                    tenant: str = None,
                                    affinity: Optional[Dict] = None,
                                    resume: Optional[Dict] = None
                                    ) -> AsyncIterator:
        """Streaming twin of assign_replica: starts a generator-valued
        call on one replica and returns an async iterator over its
        items.  The replica's in-flight slot is held for the LIFETIME of
        the stream (a generating request occupies engine capacity, so it
        must count against max_concurrent_queries the whole time);
        closing the iterator early cancels the remote stream.

        Failure contract: if the serving replica dies mid-stream the
        router fails the stream OVER to a healthy replica — resumable
        targets (serve.resumable) receive the delivered prefix and
        continue from the cursor; non-resumable targets restart only if
        nothing was delivered yet.  When failover is off/exhausted/
        unsafe the consumer gets a structured StreamInterrupted with
        the resume cursor, within the stream-RPC deadline — never a
        silent hang, and never a duplicated item.

        A target that turns out NOT to stream ran exactly once on the
        replica; with unary_fallback the iterator yields its value
        wrapped in _UnaryResult (proxy path — degrade to a plain
        response), otherwise it raises TypeError (handle.stream() on a
        unary method is caller error).

        `affinity` is the request's routing hint ({"tokens": [...]} or
        {"fps": [...]}); `resume` seeds the stream from a CLIENT-HELD
        cursor (x-rt-resume: the items a previous, interrupted stream
        already delivered, plus the dead origin's kv_origin pull
        address) — the first replica call then behaves exactly like an
        internal failover re-submission."""

        async def _gen():
            # Everything — INCLUDING slot acquisition — happens inside
            # the generator body: a stream that is closed (or dropped)
            # before its first iteration never starts this body, and an
            # unstarted generator's finally never runs, so acquiring
            # out here would leak the in-flight slot forever.
            delivered_n = 0
            # Items retained ONLY while a resume could still replay
            # them (resumable target, failover budget left) — a
            # long-lived non-resumable SSE stream must not mirror hours
            # of items in router memory for nothing.
            delivered: List[Any] = []
            exclude: tuple = ()
            failovers = 0
            resumable = False
            origin_rdv = None
            last_page = 0
            # Durable-session id: survives the whole failover chain in
            # cursors so a resumed stream can resurrect its KV pages
            # from the store even when the origin replica is long dead.
            session = (resume or {}).get("session") \
                or (affinity or {}).get("session")

            def _cursor_extras() -> Dict:
                """KV extras for an outgoing StreamInterrupted cursor:
                the origin's pull address and the request's prefix
                fingerprints (at the last-seen replica's page size), so
                a client resuming through a DIFFERENT proxy re-enters
                with affinity and can still migrate the pages."""
                out: Dict[str, Any] = {}
                if origin_rdv:
                    out["kv_origin"] = origin_rdv
                fps = (affinity or {}).get("fps")
                if not fps and affinity and affinity.get("tokens") \
                        and last_page:
                    from ray_tpu.serve.llm.paging import \
                        prefix_fingerprints
                    fps = prefix_fingerprints(
                        affinity["tokens"], last_page,
                        _cfg.serve_affinity_digest_depth)
                if fps:
                    out["digest"] = list(fps)
                if session:
                    out["session"] = session
                return out

            if resume:
                # Client-held cursor: only its UNDELIVERED suffix flows
                # from here on — delivered_n/items count as if this
                # router had streamed them itself.  The cursor's
                # kv_origin is honored only when it names a pull
                # address this router observed in the membership
                # broadcast (forged origins are SSRF/cache-poisoning
                # vectors; see _trusted_rdv).
                delivered = list(resume.get("items") or [])
                delivered_n = int(resume.get("delivered")
                                  or len(delivered))
                origin_rdv = self._trusted_rdv(resume.get("kv_origin"))
            while True:
                try:
                    choice = await self._acquire(timeout_s,
                                                 tenant=tenant,
                                                 exclude=exclude,
                                                 admit=failovers == 0,
                                                 hint=affinity)
                except Exception as e:
                    if failovers == 0:
                        raise
                    # Failover could not even PLACE the stream (no
                    # replica within the deadline): the contract is
                    # still a structured cursor, not a raw assignment
                    # error.
                    INTERRUPTED_COUNTER.inc(
                        tags={"deployment": self.deployment_name})
                    raise StreamInterrupted(
                        f"stream on {self.deployment_name}."
                        f"{method_name or '__call__'} interrupted "
                        f"after {delivered_n} items (failover could "
                        f"not place the stream: {e})",
                        deployment=self.deployment_name,
                        method=method_name, delivered=delivered_n,
                        resumable=resumable, cause=repr(e),
                        **_cursor_extras()) from e
                tag = choice["replica_tag"]
                actor = choice["actor"]
                last_page = int((choice.get("kv_digest") or {})
                                .get("page") or 0)
                finished = False
                stream_id = None
                try:
                    try:
                        resume_state = None
                        if delivered_n:
                            resume_state = {"delivered": delivered_n,
                                            "items": list(delivered)}
                        if origin_rdv \
                                and origin_rdv != choice.get("kv_rdv"):
                            # The dead origin's pull address rides the
                            # cursor: the resuming replica can MIGRATE
                            # the committed pages instead of
                            # re-prefilling the whole prefix.  Forwarded
                            # even at delivered=0 — an interruption
                            # before the first item still left the
                            # origin's PROMPT pages worth shipping.
                            resume_state = resume_state or \
                                {"delivered": 0, "items": []}
                            resume_state["kv_origin"] = origin_rdv
                        if session:
                            # Replica-side api.stream reads the session
                            # id out of _resume and resurrects the
                            # conversation's KV pages from the store
                            # before admission.  Forwarded even at
                            # delivered=0: a client reconnecting
                            # minutes later holds a cursor with no
                            # undelivered items but a session worth
                            # resurrecting.
                            resume_state = resume_state or \
                                {"delivered": 0, "items": []}
                            resume_state["session"] = session
                        t_assign = time.time()
                        started = await self._stream_rpc(
                            actor.handle_request_streaming.remote(
                                method_name, args, kwargs,
                                resume_state))
                        # serve.assign: replica chosen → stream started
                        # (the replica-side admission RPC round trip).
                        assign_args = {"deployment":
                                       self.deployment_name,
                                       "replica": tag,
                                       "failover": failovers,
                                       "resumed": delivered_n}
                        if choice.get("_affinity"):
                            assign_args["affinity"] = \
                                choice["_affinity"]
                        if resume_state \
                                and resume_state.get("kv_origin"):
                            assign_args["kv_origin"] = \
                                f"{origin_rdv.get('host')}:" \
                                f"{origin_rdv.get('port')}"
                        _tracing.record(
                            "serve", "serve.assign", t_assign,
                            time.time() - t_assign,
                            trace=_tracing.child_span(),
                            args=assign_args)
                        if "stream_id" not in started:
                            finished = True
                            if not unary_fallback:
                                raise TypeError(
                                    f"{self.deployment_name}."
                                    f"{method_name or '__call__'} "
                                    "returned a non-streaming result; "
                                    "use handle.remote() for unary "
                                    "calls")
                            yield _UnaryResult(started["unary"])
                            return
                        stream_id = started["stream_id"]
                        resumable = bool(started.get("resumable"))
                        keep_prefix = (self._stream_failover
                                       and resumable
                                       and failovers
                                       < self._max_failovers)
                        if not keep_prefix:
                            delivered = []
                        cursor = 0
                        while True:
                            delay = self._check_stream_failpoint()
                            if delay:
                                await asyncio.sleep(delay)
                            out = await self._stream_rpc(
                                actor.stream_next.remote(stream_id,
                                                         cursor))
                            for item in out["items"]:
                                delivered_n += 1
                                if keep_prefix:
                                    delivered.append(item)
                                yield item
                            cursor += len(out["items"])
                            if out["done"]:
                                finished = True
                                if out.get("error") is not None:
                                    raise out["error"]
                                return
                    except _death_errors() as e:
                        # Leave `finished` False: if the failure was a
                        # transport/injected fault and the replica is
                        # actually alive, the finally's fire-and-forget
                        # stream_cancel stops it generating into a
                        # stream nobody will poll again (a truly dead
                        # actor just drops the cancel).
                        self._drop_replica(tag)
                        # Remember where the dead replica's KV pages
                        # can be pulled from — the HOST may be alive
                        # even when the replica's actor transport is
                        # not (injected faults, wedged streams), and a
                        # dead process just makes the pull fail fast
                        # into re-prefill.
                        origin_rdv = choice.get("kv_rdv") or origin_rdv
                        can_failover = (
                            self._stream_failover
                            and failovers < self._max_failovers
                            and (resumable or not delivered_n))
                        if can_failover:
                            failovers += 1
                            # Annotation in the request's trace: the
                            # resumed stream keeps the SAME trace id,
                            # so the waterfall shows one request whose
                            # spans hop replicas at this marker.
                            _tracing.event(
                                "serve", "serve.failover",
                                args={"deployment":
                                      self.deployment_name,
                                      "replica_died": tag,
                                      "delivered": delivered_n,
                                      "failover": failovers,
                                      "resumable": resumable})
                            # Accumulate: this stream must NEVER retry
                            # a replica it watched die, even after the
                            # local-view suppression TTL expires (a
                            # slow controller must not cost a second
                            # failover against the same corpse).
                            exclude = tuple(set(exclude) | {tag})
                            FAILOVER_COUNTER.inc(
                                tags={"deployment":
                                      self.deployment_name})
                            logger.warning(
                                "stream on replica %s of %s died after "
                                "%d items (%s); %s on a healthy "
                                "replica (failover %d/%d)",
                                tag, self.deployment_name,
                                delivered_n, e,
                                "resuming" if delivered_n
                                else "restarting",
                                failovers, self._max_failovers)
                            continue
                        INTERRUPTED_COUNTER.inc(
                            tags={"deployment": self.deployment_name})
                        _tracing.event(
                            "serve", "serve.stream_interrupted",
                            args={"deployment": self.deployment_name,
                                  "replica_died": tag,
                                  "delivered": delivered_n})
                        raise StreamInterrupted(
                            f"stream on {self.deployment_name}."
                            f"{method_name or '__call__'} interrupted "
                            f"after {delivered_n} items "
                            f"(replica {tag} died; failover "
                            f"{'exhausted' if failovers else 'unavailable'}): {e}",
                            deployment=self.deployment_name,
                            method=method_name,
                            delivered=delivered_n,
                            resumable=resumable,
                            cause=repr(e),
                            **_cursor_extras()) from e
                finally:
                    if stream_id is not None and not finished:
                        # Early close / client gone: free the replica-
                        # side stream (and whatever slot it holds in an
                        # engine).
                        actor.stream_cancel.options(
                            num_returns=0).remote(stream_id)
                    self._release(tag)

        # Bind the CREATOR's trace context to every step: the consumer
        # may drive this generator from another task/loop (handle
        # streams), where the ambient context is empty — the replica
        # calls (and failover re-submissions) must keep linking under
        # the caller's span, one trace id for the stream's whole life.
        ctx = _tracing.current()
        gen = _gen()
        return _tracing.bind_agen(gen, ctx) if ctx is not None else gen

    def _pick(self, exclude: tuple = (),
              hint: Optional[Dict] = None) -> Optional[Dict]:
        if self._suppressed:
            now = asyncio.get_event_loop().time()
            for t, dl in list(self._suppressed.items()):
                if dl <= now:
                    del self._suppressed[t]
        free = [r for r in self._replicas
                if r["replica_tag"] not in exclude
                and r["replica_tag"] not in self._suppressed
                and self._in_flight.get(r["replica_tag"], 0)
                < r["max_concurrent_queries"]]
        if not free:
            return None
        if hint and _cfg.serve_affinity \
                and (hint.get("tokens") or hint.get("fps")):
            choice = self._pick_affinity(free, hint)
            if choice is not None:
                return choice
        if len(free) == 1:
            return free[0]
        # Power of two choices: least-loaded of two random candidates.
        a, b = random.sample(free, 2)
        return a if (self._in_flight.get(a["replica_tag"], 0)
                     <= self._in_flight.get(b["replica_tag"], 0)) else b

    def _load_norm(self, r: Dict) -> float:
        return (self._in_flight.get(r["replica_tag"], 0)
                / max(1, r["max_concurrent_queries"]))

    def _hint_fps(self, hint: Dict, page: int,
                  cache: Dict[int, List[str]]) -> List[str]:
        """The request's prefix fingerprint chain at a replica's page
        size.  Token hints are re-fingerprinted per distinct page size
        seen (cached per pick); a raw-fps hint (x-rt-affinity, resume
        cursor) only matches replicas with the page size it was minted
        at — the chained digests simply never collide otherwise."""
        tokens = hint.get("tokens")
        if tokens and page > 0:
            fps = cache.get(page)
            if fps is None:
                from ray_tpu.serve.llm.paging import prefix_fingerprints
                fps = cache[page] = prefix_fingerprints(
                    tokens, page, _cfg.serve_affinity_digest_depth)
            return fps
        return hint.get("fps") or []

    def _pick_affinity(self, free: List[Dict],
                       hint: Dict) -> Optional[Dict]:
        """Prefix-affinity scoring: per candidate,
        ``score = blend * hit_depth/chain_len - (1-blend) * load`` where
        hit_depth is the DEEPEST request fingerprint in the replica's
        published digest (fingerprints chain, so depth d present implies
        the whole d-page prefix is cached).  Returns None — falling back
        to the load-based power-of-two pick — when no candidate holds
        any prefix, or when the winner is past the hotspot bound: a
        viral prefix concentrates hits on one replica, and affinity must
        lose to overload there rather than starve it."""
        blend = _cfg.serve_affinity_blend
        fps_cache: Dict[int, List[str]] = {}
        best = best_meta = best_key = None
        for r in free:
            dig = r.get("kv_digest") or {}
            fps = self._hint_fps(hint, int(dig.get("page") or 0),
                                 fps_cache)
            if not fps:
                continue
            have = {x.get("fp"): int(x.get("t") or 0)
                    for x in (dig.get("roots") or ())}
            hits, hit_tier = 0, 0
            for d, fp in enumerate(fps, 1):
                if fp in have:
                    hits, hit_tier = d, have[fp]
            load = self._load_norm(r)
            # A tiered hit (digest entry's worst tier > T0) still saves
            # the prefill, but the replica must promote the pages back
            # into the decode pool first — weigh it below an
            # equally-deep hot hit so T0 holders win ties.
            weight = 1.0 if hit_tier == 0 else max(
                0.0, min(1.0,
                         float(_cfg.serve_affinity_tier_discount)))
            score = blend * weight * (hits / len(fps)) \
                - (1.0 - blend) * load
            key = (score, -load)
            if best_key is None or key > best_key:
                best, best_key = r, key
                best_meta = {"hits": hits, "chain": len(fps),
                             "tier": hit_tier,
                             "score": round(score, 4),
                             "load": round(load, 4)}
        if best is None or not best_meta["hits"]:
            return None
        AFFINITY_SCORE_GAUGE.set(best_meta["score"],
                                 tags={"deployment":
                                       self.deployment_name})
        if best_meta["load"] >= _cfg.serve_affinity_hotspot_bound:
            _tracing.event("serve", "serve.affinity_diverted",
                           args={"deployment": self.deployment_name,
                                 "replica": best["replica_tag"],
                                 **best_meta})
            return None
        AFFINITY_HITS_COUNTER.inc(
            tags={"deployment": self.deployment_name})
        # A shallow copy so the decision can ride to the serve.assign
        # span without mutating the shared membership info dict.
        choice = dict(best)
        choice["_affinity"] = best_meta
        return choice

    def stats(self) -> Dict:
        return {"queued": self.num_queued,
                "in_flight": sum(self._in_flight.values()),
                "num_replicas": len(self._replicas)}


class Router:
    """One per handle-holding process (proxy, driver, or other actor)."""

    def __init__(self, controller_handle, deployment_name: str,
                 loop: Optional[asyncio.AbstractEventLoop] = None,
                 qos: Any = _QOS_FROM_ENV):
        loop = loop or asyncio.get_event_loop()
        self.deployment_name = deployment_name
        self.replica_set = ReplicaSet(deployment_name, loop, qos=qos)
        self._long_poll = LongPollClient(
            controller_handle,
            {f"replicas::{deployment_name}":
                self.replica_set.update_replicas},
            loop=loop)

    async def assign_request(self, method_name: str, args: tuple,
                             kwargs: dict, tenant: str = None,
                             affinity: Optional[Dict] = None):
        return await self.replica_set.assign_replica(
            method_name, args, kwargs, tenant=tenant, affinity=affinity)

    async def assign_request_stream(self, method_name: str, args: tuple,
                                    kwargs: dict, tenant: str = None,
                                    affinity: Optional[Dict] = None,
                                    resume: Optional[Dict] = None):
        return await self.replica_set.assign_replica_stream(
            method_name, args, kwargs, tenant=tenant, affinity=affinity,
            resume=resume)

    def stop(self):
        self._long_poll.stop()
