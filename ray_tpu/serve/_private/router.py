"""Router: assigns queries to replicas, honoring max_concurrent_queries.

Reference: python/ray/serve/_private/router.py — Router (:262) +
ReplicaSet.assign_replica (:222): pick a replica with a free slot
(in-flight < max_concurrent_queries); if all are saturated, queue the
query until one frees.  Replica membership arrives via long poll.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Dict, List, Optional

from ray_tpu.serve._private.long_poll import LongPollClient

logger = logging.getLogger(__name__)


class ReplicaSet:
    """The live replicas of one deployment, with in-flight accounting."""

    def __init__(self, deployment_name: str, loop):
        self.deployment_name = deployment_name
        self._loop = loop
        self._replicas: List[Dict] = []
        self._in_flight: Dict[str, int] = {}
        self._slot_freed = asyncio.Event()
        self.num_queued = 0

    def update_replicas(self, infos: List[Dict]):
        self._replicas = list(infos)
        tags = {i["replica_tag"] for i in infos}
        self._in_flight = {t: self._in_flight.get(t, 0) for t in tags}
        self._slot_freed.set()  # membership change may free capacity

    async def assign_replica(self, method_name: str, args: tuple,
                             kwargs: dict,
                             timeout_s: float = 120.0) -> Any:
        """Pick a replica (power-of-two-choices among free ones), send the
        query, and release the slot when it completes.  Bounded: a request
        that can't be assigned within timeout_s (no replicas — deployment
        deleted or all crashed) errors instead of hanging forever."""
        import time as _time
        deadline = _time.monotonic() + timeout_s
        self.num_queued += 1
        try:
            while True:
                choice = self._pick()
                if choice is not None:
                    break
                remain = deadline - _time.monotonic()
                if remain <= 0:
                    raise RuntimeError(
                        f"no available replica for deployment "
                        f"{self.deployment_name!r} within {timeout_s}s")
                self._slot_freed.clear()
                try:
                    await asyncio.wait_for(self._slot_freed.wait(),
                                           timeout=min(remain, 5.0))
                except asyncio.TimeoutError:
                    pass  # re-check membership; maybe replicas arrived
        finally:
            self.num_queued -= 1
        tag = choice["replica_tag"]
        self._in_flight[tag] = self._in_flight.get(tag, 0) + 1
        try:
            actor = choice["actor"]
            ref = actor.handle_request.remote(method_name, args, kwargs)
            # ref.future() rides the CoreWorker IO loop, so this await is
            # safe on any loop (the router often runs on its own thread).
            return await asyncio.wrap_future(ref.future())
        finally:
            if tag in self._in_flight:
                self._in_flight[tag] -= 1
            self._slot_freed.set()

    def _pick(self) -> Optional[Dict]:
        free = [r for r in self._replicas
                if self._in_flight.get(r["replica_tag"], 0)
                < r["max_concurrent_queries"]]
        if not free:
            return None
        if len(free) == 1:
            return free[0]
        # Power of two choices: least-loaded of two random candidates.
        a, b = random.sample(free, 2)
        return a if (self._in_flight.get(a["replica_tag"], 0)
                     <= self._in_flight.get(b["replica_tag"], 0)) else b

    def stats(self) -> Dict:
        return {"queued": self.num_queued,
                "in_flight": sum(self._in_flight.values()),
                "num_replicas": len(self._replicas)}


class Router:
    """One per handle-holding process (proxy, driver, or other actor)."""

    def __init__(self, controller_handle, deployment_name: str,
                 loop: Optional[asyncio.AbstractEventLoop] = None):
        loop = loop or asyncio.get_event_loop()
        self.deployment_name = deployment_name
        self.replica_set = ReplicaSet(deployment_name, loop)
        self._long_poll = LongPollClient(
            controller_handle,
            {f"replicas::{deployment_name}":
                self.replica_set.update_replicas},
            loop=loop)

    async def assign_request(self, method_name: str, args: tuple,
                             kwargs: dict):
        return await self.replica_set.assign_replica(
            method_name, args, kwargs)

    def stop(self):
        self._long_poll.stop()
