"""Deployment state reconciliation: target state vs running replicas.

Reference: python/ray/serve/_private/deployment_state.py — DeploymentState
(:897) with the STARTING/RUNNING/STOPPING replica sets, DeploymentStateManager
(:1567) driving update() every control-loop tick, ActorReplicaWrapper (:162)
hiding the actor lifecycle.  Rolling updates: new-version replicas start
first; old-version replicas stop only as new ones become ready, so serving
capacity never drops to zero (zero-downtime rollout).
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import GLOBAL_CONFIG as _cfg
from ray_tpu.serve.config import DeploymentConfig, ReplicaConfig
from ray_tpu.util import metrics as _metrics

logger = logging.getLogger(__name__)

STARTING = "STARTING"
RUNNING = "RUNNING"
DRAINING = "DRAINING"
STOPPING = "STOPPING"

DRAINING_GAUGE = _metrics.Gauge(
    "serve_replica_draining",
    "Replicas draining (no new admissions, finishing in-flight work "
    "before retirement)",
    tag_keys=("deployment",))


class ReplicaWrapper:
    """One replica actor's lifecycle (reference: ActorReplicaWrapper)."""

    def __init__(self, deployment_name: str, version: str,
                 config: DeploymentConfig, replica_config: ReplicaConfig):
        self.deployment_name = deployment_name
        self.version = version
        self.replica_tag = f"{deployment_name}#{uuid.uuid4().hex[:8]}"
        self.state = STARTING
        self._config = config
        self._replica_config = replica_config
        self._actor = None
        self._ready_ref = None
        self._drain_ref = None

    def start(self):
        from ray_tpu.serve._private.replica import RTServeReplica
        opts = dict(self._replica_config.ray_actor_options or {})
        opts.setdefault("num_cpus", 0.1)
        opts.setdefault("name",
                        f"SERVE_REPLICA::{self.replica_tag}")
        opts.setdefault("max_concurrency", 1000)
        cls = ray_tpu.remote(RTServeReplica)
        self._actor = cls.options(**opts).remote(
            self.deployment_name, self.replica_tag,
            self._replica_config.deployment_def,
            self._replica_config.init_args,
            self._replica_config.init_kwargs,
            self._config.user_config, self.version)
        # Readiness probe: resolves when __init__ + reconfigure finished.
        self._ready_ref = self._actor.get_metadata.remote()

    def check_ready(self) -> Optional[bool]:
        """None = still starting, True = ready, False = failed."""
        done, _ = ray_tpu.wait([self._ready_ref], num_returns=1, timeout=0)
        if not done:
            return None
        try:
            ray_tpu.get(self._ready_ref, timeout=1)
            self.state = RUNNING
            return True
        except Exception as e:
            logger.warning("replica %s failed to start: %s",
                           self.replica_tag, e)
            return False

    def reconfigure(self, user_config, version: str):
        self.version = version
        return self._actor.reconfigure.remote(user_config, version)

    def begin_stop(self, timeout_s: float):
        self.state = STOPPING
        if self._actor is not None:
            self._drain_ref = self._actor.prepare_for_shutdown.remote(
                timeout_s)

    def check_stopped(self) -> bool:
        if self._actor is None:
            return True
        if self._drain_ref is not None:
            done, _ = ray_tpu.wait([self._drain_ref], num_returns=1,
                                   timeout=0)
            if not done:
                return False
        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass
        self._actor = None
        return True

    def running_info(self) -> Dict:
        info = {
            "replica_tag": self.replica_tag,
            "deployment": self.deployment_name,
            "version": self.version,
            "actor": self._actor,
            "max_concurrent_queries": self._config.max_concurrent_queries,
        }
        # KV-affinity extras piggyback on the load sample the autoscale
        # poll already collects: the replica's prefix digest (what it
        # has cached) and its migration pull address.  Routers receive
        # them with the membership broadcast — no extra poll plane.
        load = self.last_load
        if load:
            for key in ("kv_digest", "kv_rdv"):
                if load.get(key):
                    info[key] = load[key]
        return info

    def num_ongoing(self) -> Optional[int]:
        try:
            return ray_tpu.get(self._actor.num_ongoing_requests.remote(),
                               timeout=2)
        except Exception:
            return None

    _load_ref = None
    _load_sent_at = 0.0
    last_load: Optional[Dict] = None

    def poll_load(self, now: float) -> Optional[Dict]:
        """Non-blocking load tracking (the autoscaler's input): fire a
        get_autoscale_metrics probe, collect it on a later tick, and
        always answer from the cached last sample — one hung replica
        must never stall the control loop the way a blocking get
        would."""
        if self._actor is None:
            return self.last_load
        if self._load_ref is None:
            self._load_ref = \
                self._actor.get_autoscale_metrics.remote()
            self._load_sent_at = now
            return self.last_load
        done, _ = ray_tpu.wait([self._load_ref], num_returns=1,
                               timeout=0)
        if done:
            try:
                self.last_load = ray_tpu.get(self._load_ref, timeout=1)
            except Exception:
                pass  # keep the previous sample; health checks judge
            self._load_ref = None
        elif now - self._load_sent_at > 10.0:
            self._load_ref = None  # probe lost; re-fire next tick
        return self.last_load

    _drain_deadline = 0.0
    _drain_started = 0.0

    def begin_drain(self, now: float, timeout_s: float):
        """Scale-down path: stop admitting (the reconciler's broadcast
        only carries RUNNING replicas, so routers drop this one on the
        next long-poll) and let in-flight work — including long-lived
        streams — finish before the actor is retired."""
        self.state = DRAINING
        self._drain_started = now
        self._drain_deadline = now + timeout_s
        # Timeline annotation: scale-downs show up against the serve
        # spans they displace (controller process ring).
        _tracing.event("serve", "serve.drain",
                       args={"replica": self.replica_tag,
                             "timeout_s": timeout_s})
        # Demand a FRESH ongoing sample before declaring the drain
        # complete: the pre-drain cached value predates the routers
        # learning this replica left the broadcast — and an in-flight
        # probe fired pre-drain would repopulate it, so drop that too.
        self.last_load = None
        self._load_ref = None

    def offer_kv_migration(self, dest: "ReplicaWrapper"):
        """Drain handoff: offer this (DRAINING) replica's hot KV pages
        to a surviving replica before teardown.  The origin serves a
        manifest (pull address + hottest cached prefixes, still
        referenced by its radix tree); the SURVIVOR pulls the pages
        over the transfer plane.  Copies, not moves — the origin's
        pages stay intact until its normal teardown, so an un-drain
        mid-flight cannot double-count anything, and a non-KV
        deployment simply fails the manifest RPC (swallowed here).
        The manifest fetch is bounded (2s); the pull itself is
        fire-and-forget on the survivor."""
        if self._actor is None or dest._actor is None:
            return
        try:
            manifest = ray_tpu.get(
                self._actor.handle_request.remote(
                    "kv_drain_manifest", (), {}), timeout=2)
        except Exception:
            return
        if not manifest:
            return
        _tracing.event("serve", "serve.drain_migrate",
                       args={"origin": self.replica_tag,
                             "dest": dest.replica_tag,
                             "prefixes":
                                 len(manifest.get("prefixes", ()))})
        logger.info("drain: offering %d hot prefixes of %s to %s",
                    len(manifest.get("prefixes", ())),
                    self.replica_tag, dest.replica_tag)
        dest._actor.handle_request.options(num_returns=0).remote(
            "kv_pull_from", (manifest,), {})

    def confirmed_idle(self, now: float) -> bool:
        """A FRESH post-drain sample confirms zero in-flight work.  The
        ≥1s age floor covers the window in which a router that has not
        yet seen the membership change can still assign work — the ONE
        idle-confirmation rule, shared by drain completion and the
        un-drain gate (both would oversubscribe on a stale sample)."""
        load = self.poll_load(now)
        return (now - self._drain_started >= 1.0
                and load is not None and load.get("ongoing", 1) == 0)

    def drain_complete(self, now: float) -> bool:
        """True once the replica reports zero in-flight requests (or
        the drain deadline passed — a wedged stream must not pin a
        retired replica forever)."""
        if now >= self._drain_deadline:
            logger.warning("replica %s drain timed out; stopping with "
                           "work in flight", self.replica_tag)
            return True
        return self.confirmed_idle(now)

    _health_ref = None
    _health_sent_at = 0.0

    def poll_health(self, now: float) -> bool:
        """Non-blocking health tracking: fire a probe, poll it on later
        ticks.  Returns False when the replica must be replaced (probe
        errored or outlived health_check_timeout_s).  One hung replica
        must never stall the control loop (reference tracks health the
        same way: deployment_state.py check_started/health polling)."""
        if self._actor is None:
            return False
        if self._health_ref is None:
            self._health_ref = self._actor.check_health.remote()
            self._health_sent_at = now
            return True
        done, _ = ray_tpu.wait([self._health_ref], num_returns=1, timeout=0)
        if not done:
            if now - self._health_sent_at \
                    > self._config.health_check_timeout_s:
                return False
            return True
        try:
            ray_tpu.get(self._health_ref, timeout=1)
            self._health_ref = None
            return True
        except Exception:
            return False


class DeploymentState:
    """Reconciles one deployment (reference: deployment_state.py:897)."""

    def __init__(self, name: str, long_poll_host):
        self.name = name
        self._long_poll = long_poll_host
        self.target_config: Optional[DeploymentConfig] = None
        self.target_replica_config: Optional[ReplicaConfig] = None
        self.target_version: Optional[str] = None
        self.target_num_replicas = 0
        self.deleting = False
        self.replicas: List[ReplicaWrapper] = []
        self._last_health_check = 0.0
        self._last_broadcast: Any = None
        self._digest_fp: Any = None
        self._digest_fp_t = 0.0
        self._start_failures = 0
        self.deploy_failed = False

    # ------------------------------------------------------------- target
    def deploy(self, config: DeploymentConfig,
               replica_config: ReplicaConfig, version: str):
        self.target_config = config
        self.target_replica_config = replica_config
        self.target_version = version
        self.deleting = False
        self._start_failures = 0
        self.deploy_failed = False
        if config.autoscaling_config is not None:
            lo = config.autoscaling_config.min_replicas
            hi = config.autoscaling_config.max_replicas
            self.target_num_replicas = min(
                max(self.target_num_replicas or lo, lo), hi)
        else:
            self.target_num_replicas = config.num_replicas

    def delete(self):
        self.deleting = True
        self.target_num_replicas = 0

    def set_target_num_replicas(self, n: int):
        """Autoscaler entry point."""
        self.target_num_replicas = n

    # ---------------------------------------------------------- reconcile
    def update(self) -> bool:
        """One reconciliation tick.  Returns True while work is pending."""
        cfg = self.target_config
        if cfg is None:
            return False
        # 1. Promote replicas that finished starting; drop failed ones.
        for r in list(self.replicas):
            if r.state == STARTING:
                ready = r.check_ready()
                if ready is False:
                    self.replicas.remove(r)
                    self._start_failures += 1
                    if self._start_failures >= 3:
                        # Constructor keeps failing: stop respawning 10x/s
                        # forever (reference: DEPLOY_FAILED after bounded
                        # attempts, deployment_state.py).
                        self.deploy_failed = True
                        logger.error(
                            "deployment %s marked DEPLOY_FAILED after %d "
                            "consecutive replica start failures",
                            self.name, self._start_failures)
                elif ready is True:
                    self._start_failures = 0
            elif r.state == STOPPING:
                if r.check_stopped():
                    self.replicas.remove(r)
            elif r.state == DRAINING:
                # A delete arriving mid-drain downgrades the drain to a
                # plain graceful stop — teardown must not wait out the
                # (much longer) drain window.
                if self.deleting \
                        or r.drain_complete(time.monotonic()):
                    r.begin_stop(cfg.graceful_shutdown_timeout_s)

        running = [r for r in self.replicas if r.state == RUNNING]
        starting = [r for r in self.replicas if r.state == STARTING]

        # 2. Version rollout: light config change (user_config only) is
        # applied in place; a code/version change replaces replicas, new
        # before old (zero downtime).
        stale = [r for r in running if r.version != self.target_version]
        fresh = [r for r in running + starting
                 if r.version == self.target_version]
        # Start new-version replicas up to the target count — but first
        # UN-DRAIN: a same-version replica mid-drain still has a warm
        # model resident; re-admitting it is strictly cheaper than
        # paying a cold start because the autoscaler flapped.
        want_new = 0 if self.deploy_failed \
            else self.target_num_replicas - len(fresh)
        if want_new > 0:
            now_ud = time.monotonic()
            for r in self.replicas:
                if want_new <= 0:
                    break
                if r.state == DRAINING \
                        and r.version == self.target_version:
                    # Only un-drain a replica CONFIRMED idle: routers
                    # reset a re-broadcast replica's in-flight count to
                    # zero, so re-admitting one with live streams would
                    # oversubscribe it past max_concurrent_queries.
                    if not r.confirmed_idle(now_ud):
                        continue
                    _tracing.event("serve", "serve.undrain",
                                   args={"replica": r.replica_tag})
                    logger.info("un-draining replica %s (target rose "
                                "back)", r.replica_tag)
                    r.state = RUNNING
                    want_new -= 1
        for _ in range(max(0, want_new)):
            r = ReplicaWrapper(self.name, self.target_version, cfg,
                               self.target_replica_config)
            r.start()
            self.replicas.append(r)
        # Stop stale replicas only when enough fresh ones are RUNNING to
        # keep capacity (rolling).
        fresh_running = [r for r in running
                         if r.version == self.target_version]
        allow_stop = min(len(stale),
                         max(0, len(fresh_running) + len(stale)
                             - self.target_num_replicas))
        for r in stale[:allow_stop]:
            r.begin_stop(cfg.graceful_shutdown_timeout_s)

        # 3. Scale down surplus same-version replicas: DRAIN, don't
        # kill — the replica leaves the router broadcast immediately
        # (no new admissions) but finishes its in-flight requests and
        # streams before retirement.  Least-loaded replicas drain
        # first so the fewest streams ride out a drain window.
        now = time.monotonic()
        fresh_running = [r for r in self.replicas
                         if r.state == RUNNING
                         and r.version == self.target_version]
        excess = len(fresh_running) - self.target_num_replicas
        if excess > 0:
            if self.deleting:
                # Deployment deletion: the owner asked for it to go —
                # graceful stop (bounded by graceful_shutdown_timeout_s)
                # rather than a long admission-less drain.
                for r in fresh_running[:excess]:
                    r.begin_stop(cfg.graceful_shutdown_timeout_s)
            else:
                def _load_key(r):
                    load = r.poll_load(now)
                    return load.get("ongoing", 0) if load else 0
                victims = sorted(fresh_running, key=_load_key)[:excess]
                survivors = [r for r in fresh_running
                             if r not in victims]
                for r in victims:
                    r.begin_drain(now, cfg.drain_timeout_s)
                    if survivors and _cfg.serve_affinity:
                        # Re-home the drained replica's hot KV pages on
                        # the least-loaded survivor so its cached
                        # prefixes outlive the scale-down.
                        r.offer_kv_migration(
                            min(survivors, key=_load_key))

        # 4. Health checks on running replicas (periodic, non-blocking).
        now = time.monotonic()
        if now - self._last_health_check > cfg.health_check_period_s:
            self._last_health_check = now
            # DRAINING replicas are health-checked too: one that DIES
            # mid-drain must be reaped now, not after the full drain
            # timeout expires against a corpse.
            for r in [x for x in self.replicas
                      if x.state in (RUNNING, DRAINING)]:
                if not r.poll_health(now):
                    logger.warning("replica %s unhealthy; replacing",
                                   r.replica_tag)
                    r.state = STOPPING
                    r.check_stopped()
                    if r in self.replicas:
                        self.replicas.remove(r)

        # The affinity digest rides the load sample the AUTOSCALER
        # polls — but a fixed-replica deployment has no autoscaler, so
        # poll here too or its digests would never leave the replicas.
        # Non-blocking with at most one outstanding probe per replica,
        # same cost profile as the autoscale path.
        if _cfg.serve_affinity:
            for r in self.replicas:
                if r.state == RUNNING:
                    r.poll_load(now)

        # 5. Broadcast the running-replica set on change (a DRAINING
        # replica's exclusion here IS the "stop admitting" edge).
        DRAINING_GAUGE.set(
            sum(r.state == DRAINING for r in self.replicas),
            tags={"deployment": self.name})
        infos = [r.running_info() for r in self.replicas
                 if r.state == RUNNING]
        fingerprint: Any = sorted((i["replica_tag"], i["version"])
                                  for i in infos)
        # The affinity digests ride the same broadcast, but re-notifying
        # every router each time any replica touches any prefix would
        # turn the long-poll into a firehose: fold the digests into the
        # fingerprint at most once per serve_affinity_refresh_s —
        # membership changes still broadcast instantly, digest drift is
        # batched (stale affinity only costs a suboptimal pick).
        if _cfg.serve_affinity:
            now_b = time.monotonic()
            if now_b - self._digest_fp_t >= _cfg.serve_affinity_refresh_s:
                self._digest_fp_t = now_b
                self._digest_fp = sorted(
                    (i["replica_tag"],
                     tuple(sorted(
                         r.get("fp", "") for r in
                         (i.get("kv_digest") or {}).get("roots", ()))))
                    for i in infos)
            fingerprint = (fingerprint, self._digest_fp)
        if fingerprint != self._last_broadcast:
            self._last_broadcast = fingerprint
            self._long_poll.notify_changed(
                f"replicas::{self.name}", infos)

        pending = bool(
            [r for r in self.replicas
             if r.state != RUNNING]) or self.target_num_replicas != len(
            [r for r in self.replicas if r.state == RUNNING])
        return pending

    def curr_status(self) -> Dict:
        by_state: Dict[str, int] = {}
        for r in self.replicas:
            by_state[r.state] = by_state.get(r.state, 0) + 1
        healthy = (not self.deleting
                   and by_state.get(RUNNING, 0) == self.target_num_replicas
                   and by_state.get(STARTING, 0) == 0
                   and by_state.get(DRAINING, 0) == 0
                   and by_state.get(STOPPING, 0) == 0)
        status = "HEALTHY" if healthy else \
            ("DELETING" if self.deleting else "UPDATING")
        if self.deploy_failed:
            status = "DEPLOY_FAILED"
        return {"name": self.name, "version": self.target_version,
                "target_num_replicas": self.target_num_replicas,
                "replica_states": by_state,
                "status": status}


class DeploymentStateManager:
    """All deployments (reference: deployment_state.py:1567)."""

    def __init__(self, long_poll_host):
        self._long_poll = long_poll_host
        self._deployments: Dict[str, DeploymentState] = {}

    def deploy(self, name: str, config: DeploymentConfig,
               replica_config: ReplicaConfig, version: str,
               route_prefix: str = None):
        ds = self._deployments.get(name)
        if ds is None:
            ds = self._deployments[name] = DeploymentState(
                name, self._long_poll)
        ds.route_prefix = route_prefix or f"/{name}"
        ds.deploy(config, replica_config, version)
        self._broadcast_routes()

    def delete(self, name: str):
        ds = self._deployments.get(name)
        if ds is not None:
            ds.delete()
        self._broadcast_routes()

    def _broadcast_routes(self):
        # Route table: URL prefix -> deployment (reference: the proxy's
        # route_prefix matching).
        self._long_poll.notify_changed(
            "routes", {getattr(ds, "route_prefix", f"/{name}"): name
                       for name, ds in self._deployments.items()
                       if not ds.deleting})

    def update(self) -> bool:
        pending = False
        for name, ds in list(self._deployments.items()):
            pending |= ds.update()
            if ds.deleting and not ds.replicas:
                del self._deployments[name]
        return pending

    def get(self, name: str) -> Optional[DeploymentState]:
        return self._deployments.get(name)

    def statuses(self) -> List[Dict]:
        return [ds.curr_status() for ds in self._deployments.values()]
