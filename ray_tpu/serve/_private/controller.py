"""ServeController: the Serve control plane, one detached actor.

Reference: python/ray/serve/controller.py — ServeController (:61): owns the
DeploymentStateManager and the LongPollHost, runs the reconciliation loop,
records autoscaling metrics, and answers deploy/delete/status RPCs.
Autoscaling policy per serve/_private/autoscaling_policy.py: desired =
ceil(total_ongoing / target_per_replica), clamped and delayed.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import locksan
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu.serve.config import DeploymentConfig, ReplicaConfig
from ray_tpu.serve._private.deployment_state import (
    DeploymentStateManager, RUNNING)
from ray_tpu.serve._private.long_poll import LongPollHost

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"


class _AutoscaleState:
    def __init__(self):
        self.over_since: Optional[float] = None
        self.under_since: Optional[float] = None
        self.ewma: Optional[float] = None
        self.last_decision_t: float = -1e18
        # Cluster-autopilot coupling (deployments declaring a TTFT
        # SLO): last broker-granted replica budget and when we last
        # reported attainment.  The grant survives a GCS blip — the
        # controller keeps honoring the last known budget rather than
        # scaling blind.
        self.granted: Optional[int] = None
        self.last_report_t: float = -1e18


def _replica_load(metrics: Dict, target_per_replica: float) -> float:
    """One replica's demand in units of 'replicas worth of work'.

    The base signal is ongoing/target (the reference autoscaling
    policy); engine-backed replicas publish REAL saturation gauges and
    the max over them wins, so a replica whose slot pool or KV pool is
    the binding constraint holds its share of capacity even when the
    raw request count looks tame:

      * (active_slots + queue_depth) / num_slots — decode-slot pressure
        including the engine's own waiting line;
      * 1 - kv_blocks_reclaimable/kv_blocks_total — KV page pressure.
        Reclaimable counts free pages PLUS cold tree-only pages the
        tier sweeper can demote to host/store on demand: a replica
        whose pool is full of idle sessions is not saturated — the
        pages are a cache, not demand — so counting them as pressure
        would trigger phantom scale-ups.
    """
    load = metrics.get("ongoing", 0) / max(target_per_replica, 1e-9)
    num_slots = metrics.get("num_slots") or 0
    if num_slots > 0:
        load = max(load, (metrics.get("active_slots", 0)
                          + metrics.get("queue_depth", 0)) / num_slots)
    kv_total = metrics.get("kv_blocks_total") or 0
    if kv_total > 0:
        kv_avail = metrics.get("kv_blocks_reclaimable")
        if kv_avail is None:
            kv_avail = metrics.get("kv_blocks_free", kv_total)
        load = max(load, 1.0 - min(kv_avail, kv_total) / kv_total)
    return load


class ServeController:
    def __init__(self, http_host: str = "127.0.0.1", http_port: int = 0):
        import threading
        self._long_poll = LongPollHost()
        self._dsm = DeploymentStateManager(self._long_poll)
        # deploy/update/shutdown all mutate the DSM from executor threads;
        # one lock serializes them (the reconcile tick is cheap).
        self._dsm_lock = locksan.make_lock("ServeController._dsm_lock")
        self._autoscale: Dict[str, _AutoscaleState] = {}
        self._http_config = {"host": http_host, "port": http_port}
        self._shutdown = False
        self._loop_started = False

    # ------------------------------------------------------------ RPCs
    async def deploy(self, name: str, config_dict: Dict,
                     replica_config: ReplicaConfig, version: str,
                     route_prefix: str = None) -> bool:
        config = DeploymentConfig.from_dict(config_dict)

        def _do():
            with self._dsm_lock:
                self._dsm.deploy(name, config, replica_config, version,
                                 route_prefix=route_prefix)

        await asyncio.get_running_loop().run_in_executor(None, _do)
        return True

    async def delete_deployment(self, name: str) -> bool:
        def _do():
            with self._dsm_lock:
                self._dsm.delete(name)
            try:
                from ray_tpu._private.worker import global_worker
                global_worker.gcs_call(
                    "arbiter_unregister", {"wid": f"serve:{name}"},
                    timeout=5)
            except Exception:
                # Broker unreachable / never registered: the arbiter's
                # stale-report TTL reclaims the budget regardless.
                pass

        # The reconcile tick can hold the lock for seconds (blocking gets
        # on hung replicas) — never acquire it on the event loop.
        await asyncio.get_running_loop().run_in_executor(None, _do)
        return True

    async def get_deployment_statuses(self) -> List[Dict]:
        return self._dsm.statuses()

    async def get_deployment_info(self, name: str = None) -> List[Dict]:
        """Target specs for serve.get_deployment/list_deployments: the
        serialized body + config + version for each (or one) deployment."""
        out = []
        for dname, ds in self._dsm._deployments.items():
            if name is not None and dname != name:
                continue
            if ds.deleting or ds.target_replica_config is None:
                continue
            rc = ds.target_replica_config
            out.append({
                "name": dname,
                "config": ds.target_config.to_dict(),
                "deployment_def": rc.deployment_def,
                "init_args": rc.init_args,
                "init_kwargs": rc.init_kwargs,
                "ray_actor_options": rc.ray_actor_options,
                "version": ds.target_version,
                "route_prefix": getattr(ds, "route_prefix", f"/{dname}"),
            })
        return out

    async def listen_for_change(self, keys_to_snapshot_ids: Dict[str, int]):
        return await self._long_poll.listen(keys_to_snapshot_ids)

    async def wait_deployments_healthy(self, names: List[str],
                                       timeout_s: float = 120.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            statuses = {s["name"]: s for s in self._dsm.statuses()}
            if all(statuses.get(n, {}).get("status") == "HEALTHY"
                   for n in names):
                return True
            if any(statuses.get(n, {}).get("status") == "DEPLOY_FAILED"
                   for n in names):
                return False
            await asyncio.sleep(cfg.serve_health_poll_period_s)
        return False

    async def get_http_config(self) -> Dict:
        return dict(self._http_config)

    async def set_http_config(self, cfg: Dict):
        self._http_config.update(cfg)
        return True

    async def graceful_shutdown(self):
        self._shutdown = True

        def _delete_all():
            with self._dsm_lock:
                for s in self._dsm.statuses():
                    self._dsm.delete(s["name"])

        await asyncio.get_running_loop().run_in_executor(None, _delete_all)

        def _tick():
            with self._dsm_lock:
                self._dsm.update()
                return not self._dsm.statuses()

        loop = asyncio.get_running_loop()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if await loop.run_in_executor(None, _tick):
                break
            await asyncio.sleep(cfg.serve_health_poll_period_s)
        return True

    # ----------------------------------------------------- control loop
    async def run_control_loop(self):
        """Fire-and-forget from serve.start(); reconciles forever
        (reference: controller.py run_control_loop)."""
        if self._loop_started:
            return
        self._loop_started = True
        loop = asyncio.get_running_loop()

        def _tick():
            with self._dsm_lock:
                self._dsm.update()
                self._autoscale_tick()

        while not self._shutdown:
            try:
                # Reconciliation does sync waits/kills: run off-loop so
                # deploy/listen RPCs stay responsive.
                await loop.run_in_executor(None, _tick)
            except Exception:
                logger.exception("control loop tick failed")
            await asyncio.sleep(cfg.serve_control_loop_period_s)

    def _autoscale_tick(self):
        """Scale targets from the replicas' REAL saturation gauges
        (ongoing requests always; engine queue depth / slot occupancy /
        KV free pages where published), with three layers of flap
        suppression so chaos-induced gauge noise cannot thrash replica
        counts: an EWMA over the load signal, the sustained
        over/under delays, and a post-decision cooldown window."""
        now = time.monotonic()
        for status in self._dsm.statuses():
            name = status["name"]
            ds = self._dsm.get(name)
            if ds is None or ds.target_config is None:
                continue
            ac = ds.target_config.autoscaling_config
            if ac is None or ds.deleting:
                continue
            running = [r for r in ds.replicas if r.state == RUNNING]
            if not running:
                continue
            total_load = 0.0
            samples = 0
            ttft_p99 = None
            for r in running:
                m = r.poll_load(now)  # non-blocking, cached
                if m is None:
                    continue
                samples += 1
                total_load += _replica_load(
                    m, ac.target_num_ongoing_requests_per_replica)
                t = m.get("ttft_p99_s")
                if t is not None:
                    # Worst replica's p99 TTFT is the deployment's SLO
                    # attainment signal for the autopilot broker.
                    ttft_p99 = max(ttft_p99 or 0.0, float(t))
            if samples == 0:
                continue  # no gauge data yet; never scale blind
            st = self._autoscale.setdefault(name, _AutoscaleState())
            alpha = min(max(ac.load_ewma_alpha, 0.0), 1.0)
            if st.ewma is None or alpha >= 1.0:
                st.ewma = total_load
            else:
                st.ewma = alpha * total_load + (1 - alpha) * st.ewma
            desired = math.ceil(st.ewma * ac.smoothing_factor)
            desired = min(max(desired, ac.min_replicas), ac.max_replicas)
            if getattr(ac, "slo_ttft_p99_s", None) is not None:
                desired = self._arbiter_cap(name, ac, desired,
                                            len(running), ttft_p99, now)
            cur = ds.target_num_replicas
            in_cooldown = (now - st.last_decision_t
                           < ac.decision_cooldown_s)
            if desired > cur:
                st.under_since = None
                if st.over_since is None:
                    st.over_since = now
                if now - st.over_since >= ac.upscale_delay_s \
                        and not in_cooldown:
                    logger.info("autoscale %s: %d -> %d (load=%.2f)",
                                name, cur, desired, st.ewma)
                    ds.set_target_num_replicas(desired)
                    st.over_since = None
                    st.last_decision_t = now
            elif desired < cur:
                st.over_since = None
                if st.under_since is None:
                    st.under_since = now
                if now - st.under_since >= ac.downscale_delay_s \
                        and not in_cooldown:
                    logger.info("autoscale %s: %d -> %d (load=%.2f)",
                                name, cur, desired, st.ewma)
                    ds.set_target_num_replicas(desired)
                    st.under_since = None
                    st.last_decision_t = now
            else:
                st.over_since = st.under_since = None

    def _arbiter_cap(self, name: str, ac, desired: int, running: int,
                     ttft_p99: Optional[float], now: float) -> int:
        """Autopilot coupling for SLO-declaring deployments: report
        demand + p99 TTFT attainment to the GCS broker (one RPC per
        autopilot_report_period_s — the report doubles as the grant
        fetch) and cap the scale target at the granted budget, never
        below min_replicas.  Runs on the executor tick thread, so the
        blocking RPC never touches the controller's event loop."""
        st = self._autoscale.setdefault(name, _AutoscaleState())
        if now - st.last_report_t >= cfg.autopilot_report_period_s:
            st.last_report_t = now
            signals = {}
            if ttft_p99 is not None:
                signals["ttft_p99_s"] = ttft_p99
            try:
                from ray_tpu._private.worker import global_worker
                reply = global_worker.gcs_call("arbiter_report", {
                    "wid": f"serve:{name}", "want": desired,
                    "units_now": running, "signals": signals,
                    "decl": {"kind": "serve",
                             "priority": getattr(ac, "priority", 100),
                             "min_units": ac.min_replicas,
                             "max_units": ac.max_replicas,
                             "slo": ac.slo_ttft_p99_s}}, timeout=5)
                if isinstance(reply, dict) and reply.get("ok"):
                    st.granted = int(reply.get("granted", desired))
            except Exception:
                # GCS blip: keep honoring the last known grant rather
                # than scaling blind past the broker's budget.
                pass
        if st.granted is not None:
            desired = max(min(desired, st.granted), ac.min_replicas)
        return desired
