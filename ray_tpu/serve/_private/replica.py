"""The replica actor: hosts one copy of the user's deployment.

Reference: python/ray/serve/_private/replica.py — RayServeReplica (:231)
wrapping the user callable (:57 create_replica_wrapper), handle_request
dispatch, reconfigure(user_config), health checks.  TPU-native detail:
replicas that request TPU resources are leased TPU workers, so jax inits
the chip inside the replica process and models stay resident in HBM
between requests.
"""

from __future__ import annotations

import asyncio
import inspect
import os
import pickle
import time
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu._private import tracing as _tracing


class Request:
    """Minimal HTTP-ish request container handed to deployments reached
    through the proxy (reference passes a starlette Request)."""

    __slots__ = ("method", "path", "query", "body", "headers")

    def __init__(self, method: str = "GET", path: str = "/",
                 query: Optional[Dict[str, str]] = None, body: bytes = b"",
                 headers: Optional[Dict[str, str]] = None):
        self.method = method
        self.path = path
        self.query = query or {}
        self.body = body
        self.headers = headers or {}

    def json(self):
        import json
        return json.loads(self.body or b"null")

    def __reduce__(self):
        return (Request, (self.method, self.path, self.query, self.body,
                          self.headers))


class RTServeReplica:
    """Actor class for one replica (created by the controller)."""

    def __init__(self, deployment_name: str, replica_tag: str,
                 serialized_def: bytes, init_args: tuple,
                 init_kwargs: dict, user_config: Any, version: str):
        self.deployment_name = deployment_name
        self.replica_tag = replica_tag
        self.version = version
        self._num_ongoing = 0
        self._num_processed = 0
        self._streams: Dict[str, Dict[str, Any]] = {}
        self._stream_seq = 0
        # method name -> (target, is_async): the per-request getattr +
        # inspect.iscoroutinefunction probes are paid once per method,
        # not once per call (the unary fast path).
        self._target_cache: Dict[str, tuple] = {}
        from concurrent.futures import ThreadPoolExecutor
        self._sync_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix=f"replica-{replica_tag}")
        body = cloudpickle.loads(serialized_def)
        # Publish the replica context BEFORE user __init__ runs, so the
        # constructor itself can call serve.get_replica_context()
        # (reference: replica.py sets it in create_replica_wrapper).
        from ray_tpu.serve import context as _serve_ctx
        _serve_ctx._set_internal_replica_context(
            deployment_name, replica_tag)
        if inspect.isclass(body):
            self.callable = body(*init_args, **init_kwargs)
        else:
            self.callable = body
        _serve_ctx._set_internal_replica_context(
            deployment_name, replica_tag, servable_object=self.callable)
        if user_config is not None:
            self._reconfigure_sync(user_config)

    def _reconfigure_sync(self, user_config):
        rc = getattr(self.callable, "reconfigure", None)
        if rc is None:
            raise ValueError(
                f"{self.deployment_name}: user_config set but deployment "
                "has no reconfigure(user_config) method")
        rc(user_config)

    def reconfigure(self, user_config, version: str):
        if user_config is not None:
            self._reconfigure_sync(user_config)
        self.version = version
        self._target_cache.clear()
        return True

    def check_health(self):
        hc = getattr(self.callable, "check_health", None)
        if hc is not None:
            hc()
        return True

    def _resolve_cached(self, method_name: str) -> tuple:
        """(target, is_async) with the inspect probes paid once per
        method name instead of once per call."""
        hit = self._target_cache.get(method_name)
        if hit is None:
            target = self._resolve_target(method_name)
            is_async = inspect.iscoroutinefunction(target) or (
                not inspect.isfunction(target)
                and not inspect.ismethod(target)
                and inspect.iscoroutinefunction(
                    getattr(target, "__call__", None)))
            hit = self._target_cache[method_name] = (target, is_async)
        return hit

    async def handle_request(self, method_name: str, args: tuple,
                             kwargs: dict):
        """One query.  `method_name` '' means call the deployment itself
        (function deployment or __call__)."""
        self._num_ongoing += 1
        try:
            target, is_async = self._resolve_cached(method_name)
            if is_async:
                return await target(*args, **kwargs)
            return await self._call_sync_target(target, args, kwargs)
        finally:
            self._num_ongoing -= 1
            self._num_processed += 1

    async def _call_target(self, target, args, kwargs):
        """Invoke a resolved target with the loop-protection rule shared
        by the unary and streaming paths: sync user code must not block
        the replica's event loop (health checks, metrics, and concurrent
        queries up to max_concurrent_queries ride the same loop)."""
        if inspect.iscoroutinefunction(target) or (
                not inspect.isfunction(target)
                and not inspect.ismethod(target)
                and inspect.iscoroutinefunction(
                    getattr(target, "__call__", None))):
            return await target(*args, **kwargs)
        return await self._call_sync_target(target, args, kwargs)

    async def _call_sync_target(self, target, args, kwargs):
        loop = asyncio.get_running_loop()
        result = await loop.run_in_executor(
            self._sync_pool, lambda: target(*args, **kwargs))
        if inspect.iscoroutine(result):
            result = await result
        return result

    # -- streaming calls ------------------------------------------------
    #
    # Async generators can't ride a single actor-call result, so a
    # streaming request is split into (1) handle_request_streaming,
    # which starts the generator, pumps it into a buffer, and returns a
    # stream id, then (2) a cursor-based stream_next long-poll that
    # drains NEW items as soon as any exist.  One long-poll returns
    # every item produced since the last poll, so a fast producer is
    # amortized (many tokens per RPC) while a slow one still delivers
    # each token the moment it appears.

    def _resolve_target(self, method_name: str):
        target = self.callable
        if method_name:
            target = getattr(self.callable, method_name)
        elif not callable(target):
            target = self.callable.__call__
        return target

    async def handle_request_streaming(self, method_name: str,
                                       args: tuple, kwargs: dict,
                                       resume: Optional[Dict] = None
                                       ) -> Dict:
        """Start a streaming query.  If the target produces an async
        generator (an `async def ... yield` method, or a coroutine
        returning an async iterable) -> {"stream_id": sid, "resumable":
        bool} to poll with stream_next.  Otherwise the call has ALREADY
        run to completion and its value rides back as {"unary": result}
        — one invocation either way, so the caller (proxy) can fall
        back to a normal response without re-running side effects.

        `resume` is the router's failover cursor ({"delivered": n,
        "items": [...]}): targets marked serve.resumable receive it as
        the `_resume` keyword and must yield only what comes AFTER the
        delivered prefix."""
        self._sweep_stale_streams()
        self._ensure_stream_sweeper()
        target = self._resolve_target(method_name)
        resumable = bool(getattr(target, "__serve_resumable__", False))
        if not resumable:
            # Proxy path resolves a callable INSTANCE (method_name ""),
            # so the marker lives on its __call__, not on the instance.
            resumable = bool(getattr(
                getattr(target, "__call__", None),
                "__serve_resumable__", False))
        if resume is not None:
            if resumable:
                kwargs = {**kwargs, "_resume": resume}
            elif resume.get("delivered") or resume.get("items"):
                raise TypeError(
                    f"{self.deployment_name}.{method_name or '__call__'}"
                    " is not resumable (mark it with @serve.resumable "
                    "to accept a failover cursor)")
            # else: a hint-only cursor (kv_origin at delivered=0) has
            # nothing to replay — dropped, the stream runs whole.
        if inspect.isasyncgenfunction(target):
            ait = target(*args, **kwargs)
        else:
            self._num_ongoing += 1
            try:
                result = await self._call_target(target, args, kwargs)
            finally:
                self._num_ongoing -= 1
            if inspect.isgenerator(result):
                # Plain `def ... yield` deployment: drive it from the
                # sync pool so a blocking body can't stall the
                # replica's event loop (and a generator must never be
                # pickled into a unary reply).
                result = self._drive_sync_generator(result)
            if not hasattr(result, "__aiter__"):
                self._num_processed += 1
                return {"unary": result}
            ait = result
        self._stream_seq += 1
        stream_id = f"{self.replica_tag}:{self._stream_seq}"
        state = {"buf": [], "done": False, "error": None,
                 "event": asyncio.Event(), "task": None,
                 "last_poll": time.monotonic()}
        self._streams[stream_id] = state
        self._num_ongoing += 1  # the slot stays held while streaming
        state["task"] = asyncio.get_running_loop().create_task(
            self._pump_stream(stream_id, ait.__aiter__()))
        return {"stream_id": stream_id, "resumable": resumable}

    # A consumer that vanishes (handle process killed, or a cancel RPC
    # lost in flight) stops polling without ever sending stream_cancel;
    # its buffered tokens would otherwise sit in _streams forever — and,
    # worse, the underlying generator would keep producing into a dead
    # buffer (an engine request burning KV pages and decode slots).
    # Any stream unpolled for this long is torn down, both at the next
    # streaming admission and by a periodic sweeper, and the teardown
    # AWAITS the pump task so the generator's finally runs (the engine
    # request is cancelled, its pages/slots reclaimed).
    STREAM_IDLE_TTL_S = float(os.environ.get("RT_SERVE_STREAM_TTL_S",
                                             "300"))
    STREAM_SWEEP_PERIOD_S = float(os.environ.get(
        "RT_SERVE_STREAM_SWEEP_S", "30"))

    _sweep_task = None

    def _ensure_stream_sweeper(self):
        """Periodic sweep: a replica whose streaming consumers all
        vanished sees no further admissions, so sweeping only on
        admission would leak the abandoned engine requests forever."""
        if self._sweep_task is None or self._sweep_task.done():
            self._sweep_task = asyncio.get_running_loop().create_task(
                self._sweep_loop())

    async def _sweep_loop(self):
        while True:
            await asyncio.sleep(self.STREAM_SWEEP_PERIOD_S)
            self._sweep_stale_streams()

    def _sweep_stale_streams(self):
        now = time.monotonic()
        stale = [sid for sid, st in self._streams.items()
                 if now - st["last_poll"] > self.STREAM_IDLE_TTL_S]
        for sid in stale:
            state = self._streams.pop(sid, None)
            if state is None:
                continue
            task = state["task"]
            if task is not None and not task.done():
                task.cancel()
                # Reap in the background: awaiting confirms the user
                # generator unwound (its finally cancels the engine
                # request, freeing KV pages + the decode slot) instead
                # of trusting a fire-and-forget cancel.
                asyncio.get_running_loop().create_task(self._reap(task))

    @staticmethod
    async def _reap(task):
        try:
            await task
        except BaseException:
            pass

    async def _drive_sync_generator(self, gen):
        """Adapt a sync generator to async: each next() runs on the
        replica's sync pool."""
        sentinel = object()
        cfut = None
        try:
            while True:
                cfut = self._sync_pool.submit(
                    lambda: next(gen, sentinel))
                item = await asyncio.wrap_future(cfut)
                cfut = None  # consumed; safe to close directly
                if item is sentinel:
                    return
                yield item
        finally:
            # On cancellation the pool thread may still be INSIDE
            # next(gen) — closing a generator mid-execution raises
            # "generator already executing" and skips its cleanup.
            # Chain the close behind the in-flight call instead.
            def _close():
                try:
                    gen.close()
                except Exception:
                    pass
            if cfut is not None and not cfut.done():
                cfut.add_done_callback(
                    lambda _f: self._sync_pool.submit(_close))
            else:
                _close()

    async def _pump_stream(self, stream_id: str, ait):
        state = self._streams[stream_id]
        t0 = time.time()
        n = 0
        try:
            async for item in ait:
                state["buf"].append(item)
                state["event"].set()
                n += 1
        except asyncio.CancelledError:
            raise
        except Exception as e:
            state["error"] = e
        finally:
            state["done"] = True
            state["event"].set()
            self._num_ongoing -= 1
            self._num_processed += 1
            # Stream-lifetime span in the REPLICA process: the pump
            # task inherited the actor-task trace context, so engine
            # stage spans and this one land in the request's trace.
            _tracing.record("serve", "serve.replica_stream", t0,
                            time.time() - t0,
                            trace=_tracing.child_span(),
                            args={"stream_id": stream_id, "items": n,
                                  "deployment": self.deployment_name})

    async def stream_next(self, stream_id: str, cursor: int,
                          timeout_s: float = 10.0) -> Dict:
        """Long-poll items[cursor:]: returns as soon as at least one new
        item exists (or the stream ends / timeout_s elapses).  The
        cursor makes polls idempotent — a retried RPC re-reads instead
        of skipping.  {"items": [...], "done": bool, "error": exc|None};
        the terminal poll (done=True with all items consumed) drops the
        server-side state."""
        state = self._streams.get(stream_id)
        if state is None:
            raise KeyError(f"unknown stream {stream_id!r} (already "
                           "finished, cancelled, or never started)")
        state["last_poll"] = time.monotonic()
        deadline = time.monotonic() + timeout_s
        while len(state["buf"]) <= cursor and not state["done"]:
            remain = deadline - time.monotonic()
            if remain <= 0:
                return {"items": [], "done": False, "error": None}
            state["event"].clear()
            try:
                await asyncio.wait_for(state["event"].wait(),
                                       timeout=remain)
            except asyncio.TimeoutError:
                return {"items": [], "done": False, "error": None}
        items = state["buf"][cursor:]
        done = state["done"]
        out = {"items": items, "done": done,
               "error": state["error"] if done else None}
        if done:
            self._streams.pop(stream_id, None)
        return out

    async def stream_cancel(self, stream_id: str) -> bool:
        """Tear a stream down early (client disconnected): cancels the
        pump task, which closes the user generator (its finally blocks
        run — e.g. the engine frees the request's slot)."""
        state = self._streams.pop(stream_id, None)
        if state is None:
            return False
        task = state["task"]
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        return True

    def get_metadata(self) -> Dict:
        return {"deployment": self.deployment_name,
                "replica_tag": self.replica_tag,
                "version": self.version}

    def num_ongoing_requests(self) -> int:
        return self._num_ongoing

    def get_autoscale_metrics(self) -> Dict:
        """Load signals for the controller's autoscaler: the in-flight
        count always, plus whatever the deployment itself publishes via
        an `autoscale_metrics()` method (the LLM engine exposes queue
        depth, slot occupancy, and KV free pages this way) — the
        controller scales on REAL saturation gauges, not just the
        request count."""
        out: Dict[str, Any] = {"ongoing": self._num_ongoing}
        am = getattr(self.callable, "autoscale_metrics", None)
        if am is not None:
            try:
                extra = am()
                if isinstance(extra, dict):
                    out.update(extra)
            except Exception:
                pass  # a broken gauge must not break autoscaling
        return out

    async def prepare_for_shutdown(self, timeout_s: float = 10.0):
        """Drain: wait for in-flight requests to finish (reference:
        replica.py graceful shutdown loop)."""
        deadline = time.monotonic() + timeout_s
        while self._num_ongoing > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        return True
