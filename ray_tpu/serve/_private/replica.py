"""The replica actor: hosts one copy of the user's deployment.

Reference: python/ray/serve/_private/replica.py — RayServeReplica (:231)
wrapping the user callable (:57 create_replica_wrapper), handle_request
dispatch, reconfigure(user_config), health checks.  TPU-native detail:
replicas that request TPU resources are leased TPU workers, so jax inits
the chip inside the replica process and models stay resident in HBM
between requests.
"""

from __future__ import annotations

import asyncio
import inspect
import pickle
import time
from typing import Any, Dict, Optional

import cloudpickle


class Request:
    """Minimal HTTP-ish request container handed to deployments reached
    through the proxy (reference passes a starlette Request)."""

    __slots__ = ("method", "path", "query", "body", "headers")

    def __init__(self, method: str = "GET", path: str = "/",
                 query: Optional[Dict[str, str]] = None, body: bytes = b"",
                 headers: Optional[Dict[str, str]] = None):
        self.method = method
        self.path = path
        self.query = query or {}
        self.body = body
        self.headers = headers or {}

    def json(self):
        import json
        return json.loads(self.body or b"null")

    def __reduce__(self):
        return (Request, (self.method, self.path, self.query, self.body,
                          self.headers))


class RTServeReplica:
    """Actor class for one replica (created by the controller)."""

    def __init__(self, deployment_name: str, replica_tag: str,
                 serialized_def: bytes, init_args: tuple,
                 init_kwargs: dict, user_config: Any, version: str):
        self.deployment_name = deployment_name
        self.replica_tag = replica_tag
        self.version = version
        self._num_ongoing = 0
        self._num_processed = 0
        from concurrent.futures import ThreadPoolExecutor
        self._sync_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix=f"replica-{replica_tag}")
        body = cloudpickle.loads(serialized_def)
        # Publish the replica context BEFORE user __init__ runs, so the
        # constructor itself can call serve.get_replica_context()
        # (reference: replica.py sets it in create_replica_wrapper).
        from ray_tpu.serve import context as _serve_ctx
        _serve_ctx._set_internal_replica_context(
            deployment_name, replica_tag)
        if inspect.isclass(body):
            self.callable = body(*init_args, **init_kwargs)
        else:
            self.callable = body
        _serve_ctx._set_internal_replica_context(
            deployment_name, replica_tag, servable_object=self.callable)
        if user_config is not None:
            self._reconfigure_sync(user_config)

    def _reconfigure_sync(self, user_config):
        rc = getattr(self.callable, "reconfigure", None)
        if rc is None:
            raise ValueError(
                f"{self.deployment_name}: user_config set but deployment "
                "has no reconfigure(user_config) method")
        rc(user_config)

    def reconfigure(self, user_config, version: str):
        if user_config is not None:
            self._reconfigure_sync(user_config)
        self.version = version
        return True

    def check_health(self):
        hc = getattr(self.callable, "check_health", None)
        if hc is not None:
            hc()
        return True

    async def handle_request(self, method_name: str, args: tuple,
                             kwargs: dict):
        """One query.  `method_name` '' means call the deployment itself
        (function deployment or __call__)."""
        self._num_ongoing += 1
        try:
            target = self.callable
            if method_name:
                target = getattr(self.callable, method_name)
            elif not callable(target):
                target = self.callable.__call__
            if inspect.iscoroutinefunction(target) or (
                    not inspect.isfunction(target)
                    and not inspect.ismethod(target)
                    and inspect.iscoroutinefunction(
                        getattr(target, "__call__", None))):
                result = await target(*args, **kwargs)
            else:
                # Sync user code must not block the replica's event loop:
                # health checks, metrics, and concurrent queries (up to
                # max_concurrent_queries) ride the same loop.
                loop = asyncio.get_running_loop()
                result = await loop.run_in_executor(
                    self._sync_pool, lambda: target(*args, **kwargs))
                if inspect.iscoroutine(result):
                    result = await result
            return result
        finally:
            self._num_ongoing -= 1
            self._num_processed += 1

    def get_metadata(self) -> Dict:
        return {"deployment": self.deployment_name,
                "replica_tag": self.replica_tag,
                "version": self.version}

    def num_ongoing_requests(self) -> int:
        return self._num_ongoing

    async def prepare_for_shutdown(self, timeout_s: float = 10.0):
        """Drain: wait for in-flight requests to finish (reference:
        replica.py graceful shutdown loop)."""
        deadline = time.monotonic() + timeout_s
        while self._num_ongoing > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        return True
