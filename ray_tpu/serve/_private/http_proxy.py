"""HTTP ingress: an aiohttp server inside an actor, routing to replicas.

Reference: python/ray/serve/_private/http_proxy.py — HTTPProxyActor (:333)
runs uvicorn in the actor's event loop; HTTPProxy.__call__ (:189) resolves
the route prefix, forwards to the deployment through a Router, and
translates the result to an HTTP response.  Here the server is aiohttp
(starlette/uvicorn are not in the image) on the actor's own loop.
"""

from __future__ import annotations

import asyncio
import json as _json
import logging
from typing import Dict, Optional

from ray_tpu._private import tracing as _tracing
from ray_tpu.serve._private.long_poll import LongPollClient
from ray_tpu.serve._private.replica import Request
from ray_tpu.serve._private.router import ReplicaSet
from ray_tpu.serve.exceptions import StreamInterrupted, TenantThrottled

logger = logging.getLogger(__name__)


def _adopt_trace_header(headers: Dict[str, str]):
    """Adopt a client-side trace context riding the `x-rt-trace`
    header ("trace_id:parent_span_id") — a driver that spans its HTTP
    call sees the proxy/replica/engine spans land in the SAME trace.
    Returns the contextvar reset token, or None."""
    hdr = next((v for k, v in (headers or {}).items()
                if k.lower() == "x-rt-trace"), None)
    if not hdr:
        return None
    try:
        tid, pid = hdr.split(":", 1)
    except ValueError:
        return None
    return _tracing.set_current(tid.strip(), pid.strip() or None)


def _throttle_response(e: TenantThrottled):
    """TenantThrottled -> structured 429: overload is an immediate,
    retryable signal at the wire (Retry-After from the token bucket),
    never queue inflation."""
    retry = str(max(1, int(e.retry_after_s + 0.999)))
    body = _json.dumps({"error": str(e), "tenant": e.tenant,
                        "reason": e.reason}).encode()
    return 429, body, "application/json", [("Retry-After", retry)]


class HTTPProxy:
    """Routing core shared by the actor and tests: route table via long
    poll, one ReplicaSet per deployment."""

    def __init__(self, controller_handle, loop):
        self._controller = controller_handle
        self._loop = loop
        self.routes: Dict[str, str] = {}   # route prefix -> deployment
        self._replica_sets: Dict[str, ReplicaSet] = {}
        self._pollers: Dict[str, LongPollClient] = {}
        self._route_poller = LongPollClient(
            controller_handle, {"routes": self._update_routes}, loop=loop)

    def _update_routes(self, routes: Dict[str, str]):
        self.routes = dict(routes or {})
        for deployment in self.routes.values():
            if deployment not in self._replica_sets:
                rs = ReplicaSet(deployment, self._loop)
                self._replica_sets[deployment] = rs
                self._pollers[deployment] = LongPollClient(
                    self._controller,
                    {f"replicas::{deployment}": rs.update_replicas},
                    loop=self._loop)

    def _match_route(self, path: str):
        """Longest-route_prefix match -> (ReplicaSet, sub-path) or None."""
        match = None
        for prefix in self.routes:
            if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                if match is None or len(prefix) > len(match):
                    match = prefix
        if match is None:
            return None
        deployment = self.routes[match]
        rest = path[len(match.rstrip("/")):] or "/"
        return self._replica_sets[deployment], rest

    @staticmethod
    def tenant_of(query: Dict[str, str],
                  headers: Dict[str, str]) -> Optional[str]:
        """Tenant key for QoS admission: the `x-tenant` header or the
        `tenant` query param; None (→ the "default" bucket) when the
        client names neither."""
        t = next((v for k, v in (headers or {}).items()
                  if k.lower() == "x-tenant"), None)
        if t:
            return str(t)
        t = (query or {}).get("tenant")
        return str(t) if t else None

    @staticmethod
    def wants_stream(query: Dict[str, str],
                     headers: Dict[str, str]) -> bool:
        """A request opts into SSE with Accept: text/event-stream or
        ?stream=1 (mirrored by streaming deployments, e.g.
        serve.llm.api._wants_stream — the proxy must pick the streaming
        transport BEFORE the replica sees the request)."""
        accept = next((v for k, v in (headers or {}).items()
                       if k.lower() == "accept"), "") or ""
        if "text/event-stream" in accept:
            return True
        return str((query or {}).get("stream", "")).lower() \
            in ("1", "true", "yes")

    @staticmethod
    def affinity_hint(body: bytes,
                      headers: Dict[str, str]) -> Optional[Dict]:
        """Routing hint for prefix-affinity: an `x-rt-affinity` header
        (comma-separated prefix fingerprints from a prior resume
        cursor) wins; otherwise a JSON body with a token-list prompt
        ("tokens", or "prompt" for OpenAI-shaped clients) is
        fingerprinted by the router per replica page size.  None means
        load-only routing."""
        hdr = next((v for k, v in (headers or {}).items()
                    if k.lower() == "x-rt-affinity"), None)
        if hdr:
            fps = [f.strip() for f in str(hdr).split(",") if f.strip()]
            if fps:
                return {"fps": fps}
        try:
            data = _json.loads(body)
        except Exception:
            return None
        if not isinstance(data, dict):
            return None
        hint = None
        toks = data.get("tokens", data.get("prompt"))
        if isinstance(toks, list) and toks \
                and all(isinstance(t, int) for t in toks):
            hint = {"tokens": toks}
        session = data.get("session")
        if isinstance(session, str) and session:
            # A durable-session id rides the hint so the router can
            # thread it through resume cursors (any replica can
            # resurrect the session from the store, so it biases
            # nothing — it just has to SURVIVE the hop).
            hint = dict(hint or {})
            hint["session"] = session
        return hint

    @staticmethod
    def resume_cursor_of(headers: Dict[str, str]) -> Optional[Dict]:
        """A client-held resume cursor riding the `x-rt-resume` header
        (the JSON this proxy handed out in a 503 body / SSE error
        event, plus the delivered items): seeds the router's stream so
        the resubmitted request continues past the cursor — across
        proxy death, since nothing about it lives in proxy state.
        A zero-delivered cursor still counts when it carries kv_origin:
        an interruption before the first item left the origin's prompt
        pages worth migrating (the router validates the address against
        its membership view before anything dials it)."""
        hdr = next((v for k, v in (headers or {}).items()
                    if k.lower() == "x-rt-resume"), None)
        if not hdr:
            return None
        try:
            cur = _json.loads(hdr)
        except Exception:
            return None
        if isinstance(cur, dict) \
                and (cur.get("items") or cur.get("delivered")
                     or cur.get("kv_origin") or cur.get("session")):
            # A session-only cursor is worth keeping too: the replica
            # resurrects the session's pages from the durable store
            # even when the origin replica is long gone.
            return cur
        return None

    async def handle_stream(self, method: str, path: str,
                            query: Dict[str, str], body: bytes,
                            headers: Dict[str, str]):
        """Start a streaming (SSE) request: returns (status, payload,
        content_type) on routing/startup failure, or (200, aiter, None)
        where `aiter` yields the deployment's items to be framed as SSE
        events by the server layer.  unary_fallback is on: a deployment
        that answers with a plain value (or a structured error like an
        overload 503) yields one _UnaryResult, which _handle_sse turns
        back into a normal response — streaming intent in the request
        must not break non-streaming deployments or error statuses."""
        matched = self._match_route(path)
        if matched is None:
            return (404, f"no route for {path!r}".encode(),
                    "text/plain", [])
        rs, rest = matched
        req = Request(method=method, path=rest,
                      query=query, body=body, headers=headers)
        try:
            aiter = await rs.assign_replica_stream(
                "", (req,), {}, unary_fallback=True,
                tenant=self.tenant_of(query, headers),
                affinity=self.affinity_hint(body, headers),
                resume=self.resume_cursor_of(headers))
        except TenantThrottled as e:
            return _throttle_response(e)
        except Exception as e:
            logger.exception("stream request to %s failed",
                             rs.deployment_name)
            return 500, repr(e).encode(), "text/plain", []
        return 200, aiter, None, []

    @staticmethod
    def format_result(result):
        """Replica result -> (status, body, content_type, header_pairs):
        the single formatting rule shared by the unary path and the
        streaming path's unary fallback."""
        if isinstance(result, dict) and result.get("__http__") is True:
            # Structured response from an ASGI ingress deployment
            # (serve.ingress) or a status-bearing deployment: honor its
            # status/headers/body.  Headers travel as a (name, value)
            # pair LIST so repeats (Set-Cookie) survive; dict-shaped
            # replicas still work.
            raw = result.get("headers") or []
            pairs = list(raw.items()) if isinstance(raw, dict) \
                else [tuple(p) for p in raw]
            return (int(result.get("status", 200)),
                    bytes(result.get("body", b"")),
                    result.get("content_type", "text/plain"),
                    pairs)
        if isinstance(result, (bytes, bytearray)):
            return 200, bytes(result), "application/octet-stream", []
        if isinstance(result, str):
            return 200, result.encode(), "text/plain", []
        try:
            return 200, _json.dumps(result).encode(), \
                "application/json", []
        except TypeError:
            return 200, repr(result).encode(), "text/plain", []

    async def handle(self, method: str, path: str, query: Dict[str, str],
                     body: bytes, headers: Dict[str, str]):
        """Longest-route_prefix match -> replica call (reference:
        http_proxy.py route matching)."""
        if path in ("", "/"):
            return 200, _json.dumps(
                {"routes": sorted(self.routes)}).encode(), "application/json"
        matched = self._match_route(path)
        if matched is None:
            return 404, f"no route for {path!r}".encode(), "text/plain"
        rs, rest = matched
        req = Request(method=method, path=rest,
                      query=query, body=body, headers=headers)
        try:
            result = await rs.assign_replica(
                "", (req,), {}, tenant=self.tenant_of(query, headers),
                affinity=self.affinity_hint(body, headers))
        except TenantThrottled as e:
            return _throttle_response(e)
        except Exception as e:
            logger.exception("request to %s failed", rs.deployment_name)
            return 500, repr(e).encode(), "text/plain"
        return self.format_result(result)


class HTTPProxyActor:
    """The actor: binds the port in __init__ via its own background loop
    bridge; serves until killed.  One per node in a full deployment
    (reference starts one per node via node-affinity scheduling)."""

    def __init__(self, host: str, port: int, controller_name: str,
                 access_log: bool = True):
        import ray_tpu
        self.host = host
        self.port = port
        self._controller = ray_tpu.get_actor(controller_name)
        self._proxy: Optional[HTTPProxy] = None
        self._runner = None
        self._site = None
        self._ready = asyncio.Event()
        # Per-request INFO lines ride the worker-log pubsub mirror to
        # the driver — useful in dev, measurable per-request cost on
        # small hosts; benchmarks turn it off (the reference's serve
        # microbenchmark also runs without access logging).
        self._access_log = access_log

    async def run(self):
        """Start the aiohttp server on the actor's event loop; returns
        once the socket is bound (callers get readiness), then serves
        until the actor dies."""
        from aiohttp import web
        loop = asyncio.get_running_loop()
        self._proxy = HTTPProxy(self._controller, loop)

        async def _handler(request: "web.Request"):
            body = await request.read()
            query = dict(request.query)
            headers_in = dict(request.headers)
            # Inbound trace context (x-rt-trace) makes the proxy span a
            # child of the CLIENT's span; otherwise serve.request roots
            # a fresh trace.  Either way the trace id is echoed back as
            # x-rt-trace-id so the client can `rt trace <id>` it.
            token = _adopt_trace_header(headers_in)
            try:
                # Root stays the routes listing whatever the Accept
                # header says — only routed paths can stream.
                if request.path not in ("", "/") \
                        and HTTPProxy.wants_stream(query, headers_in):
                    return await self._handle_sse(
                        request, body, query, headers_in,
                        fresh_root=token is None)
                with _tracing.span("serve", "serve.request",
                                   args={"method": request.method,
                                         "path": request.path},
                                   root=token is None) as h:
                    status, payload, ctype, *rest = \
                        await self._proxy.handle(
                            request.method, request.path, query, body,
                            headers_in)
                # ASGI ingress responses carry full headers (Set-Cookie,
                # Location, ...); content-type/length ride dedicated
                # kwargs.  A pair list (not a dict) feeds the
                # CIMultiDict so repeated names all reach the wire.
                raw = rest[0] if rest else []
                pairs = raw.items() if isinstance(raw, dict) else raw
                headers = [(k, v) for k, v in pairs
                           if k.lower() not in ("content-type",
                                                "content-length")]
                headers.append(("x-rt-trace-id", h.trace_id))
                return web.Response(status=status, body=payload,
                                    content_type=ctype.split(";")[0],
                                    headers=headers)
            finally:
                if token is not None:
                    _tracing.reset_current(token)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", _handler)
        kwargs = {} if self._access_log else {"access_log": None}
        # Keep-alive tuning for the proxy hop: hold client connections
        # well past the default 75 s so steady low-QPS clients never pay
        # reconnect + slow-start inside a measurement window, and keep
        # TCP keep-alive probes on so dead peers are still reaped.
        # (NODELAY is aiohttp's default on accepted sockets; the replica
        # leg already sets it in protocol.Connection.)
        kwargs["keepalive_timeout"] = 300.0
        try:
            self._runner = web.AppRunner(app, **kwargs)
        except TypeError:  # older aiohttp without keepalive_timeout
            kwargs.pop("keepalive_timeout", None)
            self._runner = web.AppRunner(app, **kwargs)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self.host, self.port)
        await self._site.start()
        # Discover the bound port (port=0 requests an ephemeral one).
        for sock in self._site._server.sockets:  # noqa: SLF001
            self.port = sock.getsockname()[1]
            break
        self._ready.set()
        return {"host": self.host, "port": self.port}

    async def _handle_sse(self, request, body: bytes,
                          query: Dict[str, str],
                          headers_in: Dict[str, str],
                          fresh_root: bool = True):
        """serve.request span wrapper for the SSE path: the span covers
        accept → stream complete, so failovers and the token loop land
        inside it; the trace id rides back on x-rt-trace-id."""
        with _tracing.span("serve", "serve.request",
                           args={"method": request.method,
                                 "path": request.path, "sse": True},
                           root=fresh_root) as h:
            return await self._handle_sse_impl(request, body, query,
                                               headers_in, h)

    async def _handle_sse_impl(self, request, body: bytes,
                               query: Dict[str, str],
                               headers_in: Dict[str, str], span):
        """Server-sent events: each item the deployment yields becomes
        one `data: <json>` event, flushed immediately (chunked transfer,
        no buffering) so the first token reaches the client while the
        rest are still being generated.  The stream ends with
        `data: [DONE]`; a mid-stream failure emits an `event: error`.

        The FIRST item is pulled before the response status is
        committed: a deployment that answers unary (not a generator —
        including structured errors like an overload 503) degrades to a
        plain response with its real status code, and a failure to even
        start the stream is a real 500, not a 200 with an error event."""
        from aiohttp import web

        from ray_tpu.serve._private.router import _UnaryResult
        tid_hdr = ("x-rt-trace-id", span.trace_id)
        status, payload, ctype, hdrs = await self._proxy.handle_stream(
            request.method, request.path, query, body, headers_in)
        if status != 200:
            return web.Response(status=status, body=payload,
                                content_type=ctype.split(";")[0],
                                headers=list(hdrs or []) + [tid_hdr])
        aiter = payload
        _empty = object()  # distinguishes "no items" from a None item
        try:
            first = await aiter.__anext__()
        except StopAsyncIteration:
            first = _empty
        except TenantThrottled as e:
            # QoS admission runs at slot acquisition (inside the
            # stream's first step): a shed BEFORE any item is a real
            # 429 at the wire, not a 200 with an error event.
            await aiter.aclose()
            status, payload, ctype, hdrs = _throttle_response(e)
            return web.Response(status=status, body=payload,
                                content_type=ctype.split(";")[0],
                                headers=hdrs + [tid_hdr])
        except StreamInterrupted as e:
            # Zero items were delivered and failover could not place
            # the stream: retryable server-side failure.  The cursor
            # also rides RESUBMIT HEADERS: a client (or LB retry hop)
            # copies x-rt-resume / x-rt-affinity onto the retry and
            # re-enters with affinity — through ANY proxy, since the
            # cursor itself is the only state.
            await aiter.aclose()
            cursor = e.resume_cursor
            hdrs = [("Retry-After", "1"), tid_hdr,
                    ("x-rt-resume", _json.dumps(cursor))]
            if cursor.get("digest"):
                hdrs.append(("x-rt-affinity",
                             ",".join(cursor["digest"])))
            return web.Response(
                status=503,
                body=_json.dumps({"error": str(e),
                                  "resume_cursor": cursor}).encode(),
                content_type="application/json",
                headers=hdrs)
        except Exception as e:
            logger.exception("stream failed before first item")
            await aiter.aclose()
            return web.Response(status=500, body=repr(e).encode(),
                                content_type="text/plain",
                                headers=[tid_hdr])
        if isinstance(first, _UnaryResult):
            await aiter.aclose()
            status, payload, ctype, pairs = HTTPProxy.format_result(
                first.value)
            headers = [(k, v) for k, v in pairs
                       if k.lower() not in ("content-type",
                                            "content-length")]
            return web.Response(status=status, body=payload,
                                content_type=ctype.split(";")[0],
                                headers=headers + [tid_hdr])
        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache",
                     "X-Accel-Buffering": "no",
                     "X-RT-Trace-Id": span.trace_id})
        await resp.prepare(request)
        try:
            if first is not _empty:
                await resp.write(b"data: "
                                 + _json.dumps(first,
                                               default=repr).encode()
                                 + b"\n\n")
                async for item in aiter:
                    data = _json.dumps(item, default=repr)
                    await resp.write(f"data: {data}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
        except ConnectionResetError:
            # Client went away: closing the iterator cancels the
            # replica-side stream (and frees its engine slot).
            pass
        except StreamInterrupted as e:
            # Mid-stream interruption after failover ran out: the
            # response status is already committed, so the contract is
            # a STRUCTURED terminal error event carrying the resume
            # cursor (delivered-item count) — the client knows exactly
            # what it has and can re-submit the remainder.
            try:
                await resp.write(
                    b"event: error\ndata: "
                    + _json.dumps({"error": "stream_interrupted",
                                   "message": str(e),
                                   "resume_cursor": e.resume_cursor}
                                  ).encode() + b"\n\n")
            except Exception:
                pass
        except Exception as e:
            try:
                await resp.write(
                    b"event: error\ndata: "
                    + _json.dumps(repr(e)).encode() + b"\n\n")
            except Exception:
                pass
        finally:
            await aiter.aclose()
        try:
            await resp.write_eof()
        except Exception:
            pass
        return resp

    async def ready(self) -> Dict:
        await self._ready.wait()
        return {"host": self.host, "port": self.port}
