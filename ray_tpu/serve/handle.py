"""DeploymentHandle: call a deployment from Python.

Reference: python/ray/serve/handle.py — RayServeHandle (:77): sync and
async callers share a Router; `handle.remote()` routes through the
replica set with max_concurrent_queries accounting.  The router lives on
a background asyncio loop so plain (sync) driver code can hold handles.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
import time
from typing import Any, Optional

from ray_tpu._private import locksan
from ray_tpu._private import tracing as _tracing

_router_loop: Optional[asyncio.AbstractEventLoop] = None
_router_loop_lock = locksan.make_lock("handle._router_loop_lock")


def _get_router_loop() -> asyncio.AbstractEventLoop:
    """Shared background event loop hosting routers + long-poll clients
    for every handle in this process."""
    global _router_loop
    with _router_loop_lock:
        if _router_loop is None or _router_loop.is_closed():
            loop = asyncio.new_event_loop()
            t = threading.Thread(target=loop.run_forever,
                                 name="serve-router", daemon=True)
            t.start()
            _router_loop = loop
        return _router_loop


class ServeResponse:
    """Future-like result of handle.remote() usable from sync and async
    code (`resp.result()` or `await resp`)."""

    def __init__(self, fut: concurrent.futures.Future):
        self._fut = fut

    def result(self, timeout: Optional[float] = None) -> Any:
        return self._fut.result(timeout)

    def __await__(self):
        return asyncio.wrap_future(self._fut).__await__()


class ServeResponseStream:
    """Streaming result of handle.stream(): iterate items as the replica
    yields them (`async for item in stream` from any event loop, or a
    plain `for item in stream` from sync code — but never a sync `for`
    ON the router loop's own thread, which would deadlock).

    The underlying async generator lives on the shared router loop;
    every step is scheduled there, so consumers on other loops/threads
    only ever wait on a local future.  Items are pulled one at a time —
    interleave two consumers and they'll steal from each other, so
    don't share a stream."""

    def __init__(self, agen_fut: concurrent.futures.Future, loop):
        self._agen_fut = agen_fut   # resolves to the async generator
        self._agen = None
        self._loop = loop
        self._closed = False
        self._pending: Optional[concurrent.futures.Future] = None
        self._partial: list = []  # result()'s drained-so-far stash

    def _step(self) -> concurrent.futures.Future:
        # A step abandoned by a timed-out/cancelled wait — even one
        # that COMPLETED right after the timeout fired — holds an
        # unconsumed item; hand it back instead of starting a second
        # concurrent __anext__ on the same generator (which would raise
        # "already running" — or silently drop that item).  _pending is
        # cleared only at consumption sites, never on wait timeout.
        if self._pending is not None:
            return self._pending

        async def _next():
            if self._agen is None:
                self._agen = await asyncio.wrap_future(self._agen_fut)
            return await self._agen.__anext__()

        self._pending = asyncio.run_coroutine_threadsafe(
            _next(), self._loop)
        return self._pending

    def __aiter__(self):
        return self

    async def __anext__(self) -> Any:
        if self._closed:
            raise StopAsyncIteration
        # StopAsyncIteration propagates through the wrapped future and
        # terminates the caller's `async for` naturally.
        fut = self._step()
        try:
            val = await asyncio.wrap_future(fut)
        except asyncio.CancelledError:
            raise  # the WAIT was cancelled; the step may still deliver
        except BaseException:
            self._pending = None  # the step itself ended/failed
            raise
        self._pending = None
        return val

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise StopIteration
        fut = self._step()
        try:
            val = fut.result()
        except StopAsyncIteration:
            self._pending = None
            raise StopIteration from None
        except BaseException:
            self._pending = None
            raise
        self._pending = None
        return val

    async def collect(self) -> list:
        """Drain the stream into a list (async)."""
        return [item async for item in self]

    def result(self, timeout: Optional[float] = None) -> list:
        """Drain the stream into a list (sync).  `timeout` bounds the
        WHOLE drain; on timeout NOTHING is lost — the in-flight step
        AND the items drained so far are kept, and a later result()
        call returns the complete list from the start."""
        deadline = None if timeout is None else time.monotonic() + timeout
        out = self._partial  # resume an earlier timed-out drain
        while True:
            remain = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            fut = self._step()
            try:
                val = fut.result(remain)
            except StopAsyncIteration:
                self._pending = None
                self._partial = []
                return list(out)
            except concurrent.futures.TimeoutError:
                raise TimeoutError(
                    f"stream still producing after {timeout}s "
                    f"({len(out)} items so far; call result() again "
                    "to resume)") from None  # _pending kept
            except BaseException:
                self._pending = None
                raise
            self._pending = None
            out.append(val)

    def close(self):
        """Stop consuming and cancel the remote stream (frees the
        replica's engine slot); idempotent."""
        try:
            asyncio.run_coroutine_threadsafe(
                self._aclose_inner(), self._loop).result(timeout=30)
        except Exception:
            pass

    async def aclose(self):
        """Async close() for use inside event-loop code."""
        await asyncio.wrap_future(
            asyncio.run_coroutine_threadsafe(self._aclose_inner(),
                                             self._loop))

    async def _aclose_inner(self):
        """The one teardown path (close() and aclose() both land here,
        on the router loop).  A step left in flight by a timed-out
        result() keeps the generator suspended inside __anext__ — and
        aclose() on a RUNNING async generator raises instead of
        closing — so the pending step is cancelled first, which unwinds
        the generator (its finally cancels the remote stream and
        releases the in-flight slot)."""
        if self._closed:
            return
        self._closed = True
        pending, self._pending = self._pending, None
        if pending is not None and not pending.done():
            pending.cancel()
            try:
                await asyncio.wrap_future(pending)
            except BaseException:
                pass
        if self._agen is None:
            try:
                self._agen = await asyncio.wrap_future(self._agen_fut)
            except Exception:
                return
        await self._agen.aclose()


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller_handle,
                 method_name: str = ""):
        self.deployment_name = deployment_name
        self._controller = controller_handle
        self._method_name = method_name
        self._router = None
        self._router_lock = locksan.make_lock(
            "DeploymentHandle._router_lock")

    def _ensure_router(self):
        if self._router is None:
            with self._router_lock:
                if self._router is None:
                    from ray_tpu.serve._private.router import Router
                    loop = _get_router_loop()
                    fut = asyncio.run_coroutine_threadsafe(
                        self._make_router(loop), loop)
                    self._router = fut.result(timeout=30)
        return self._router

    async def _make_router(self, loop):
        from ray_tpu.serve._private.router import Router
        return Router(self._controller, self.deployment_name, loop=loop)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, self._controller,
                                method_name=name)

    @staticmethod
    def _with_caller_trace(coro_fn):
        """The router loop is another thread — contextvars don't cross
        run_coroutine_threadsafe, so the CALLER's trace context is
        captured here and re-installed around the routed call: a driver
        span stays the parent of the replica's spans."""
        ctx = _tracing.current()
        if ctx is None:
            return coro_fn()

        async def _call():
            token = _tracing.set_current(*ctx)
            try:
                return await coro_fn()
            finally:
                _tracing.reset_current(token)
        return _call()

    def remote(self, *args, **kwargs) -> ServeResponse:
        router = self._ensure_router()
        loop = _get_router_loop()
        fut = asyncio.run_coroutine_threadsafe(
            self._with_caller_trace(
                lambda: router.assign_request(self._method_name, args,
                                              kwargs)), loop)
        return ServeResponse(fut)

    def stream(self, *args, **kwargs) -> ServeResponseStream:
        """Call a generator-valued deployment method and stream its
        items as they are produced (vs .remote(), which returns one
        value when the call completes):

            async for token in handle.tokens.stream(prompt): ...
            for token in handle.options("tokens").stream(prompt): ...

        The method addressed by this handle (via attribute access or
        .options()) must be an async generator on the deployment.  A
        deployment method that itself is named "stream" shadows against
        this real method — address it with handle.options("stream")."""
        router = self._ensure_router()
        loop = _get_router_loop()
        fut = asyncio.run_coroutine_threadsafe(
            self._with_caller_trace(
                lambda: router.assign_request_stream(
                    self._method_name, args, kwargs)), loop)
        return ServeResponseStream(fut, loop)

    def options(self, method_name: str = "") -> "DeploymentHandle":
        return DeploymentHandle(self.deployment_name, self._controller,
                                method_name=method_name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name, self._controller,
                                   self._method_name))


RayServeHandle = DeploymentHandle
