"""DeploymentHandle: call a deployment from Python.

Reference: python/ray/serve/handle.py — RayServeHandle (:77): sync and
async callers share a Router; `handle.remote()` routes through the
replica set with max_concurrent_queries accounting.  The router lives on
a background asyncio loop so plain (sync) driver code can hold handles.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
from typing import Any, Optional

_router_loop: Optional[asyncio.AbstractEventLoop] = None
_router_loop_lock = threading.Lock()


def _get_router_loop() -> asyncio.AbstractEventLoop:
    """Shared background event loop hosting routers + long-poll clients
    for every handle in this process."""
    global _router_loop
    with _router_loop_lock:
        if _router_loop is None or _router_loop.is_closed():
            loop = asyncio.new_event_loop()
            t = threading.Thread(target=loop.run_forever,
                                 name="serve-router", daemon=True)
            t.start()
            _router_loop = loop
        return _router_loop


class ServeResponse:
    """Future-like result of handle.remote() usable from sync and async
    code (`resp.result()` or `await resp`)."""

    def __init__(self, fut: concurrent.futures.Future):
        self._fut = fut

    def result(self, timeout: Optional[float] = None) -> Any:
        return self._fut.result(timeout)

    def __await__(self):
        return asyncio.wrap_future(self._fut).__await__()


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller_handle,
                 method_name: str = ""):
        self.deployment_name = deployment_name
        self._controller = controller_handle
        self._method_name = method_name
        self._router = None
        self._router_lock = threading.Lock()

    def _ensure_router(self):
        if self._router is None:
            with self._router_lock:
                if self._router is None:
                    from ray_tpu.serve._private.router import Router
                    loop = _get_router_loop()
                    fut = asyncio.run_coroutine_threadsafe(
                        self._make_router(loop), loop)
                    self._router = fut.result(timeout=30)
        return self._router

    async def _make_router(self, loop):
        from ray_tpu.serve._private.router import Router
        return Router(self._controller, self.deployment_name, loop=loop)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, self._controller,
                                method_name=name)

    def remote(self, *args, **kwargs) -> ServeResponse:
        router = self._ensure_router()
        loop = _get_router_loop()
        fut = asyncio.run_coroutine_threadsafe(
            router.assign_request(self._method_name, args, kwargs), loop)
        return ServeResponse(fut)

    def options(self, method_name: str = "") -> "DeploymentHandle":
        return DeploymentHandle(self.deployment_name, self._controller,
                                method_name=method_name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name, self._controller,
                                   self._method_name))


RayServeHandle = DeploymentHandle
