"""Serve public API.

Reference: python/ray/serve/api.py — @serve.deployment (deployment.py),
serve.start, serve.run (:428), serve.delete, serve.shutdown,
serve.get_deployment_handle.  The controller is a detached named actor so
deployments outlive the driver that created them.
"""

from __future__ import annotations

import inspect
import logging
import uuid
from typing import Any, Callable, Dict, List, Optional, Union

import cloudpickle

import ray_tpu
from ray_tpu.serve.config import (AutoscalingConfig, DeploymentConfig,
                                  ReplicaConfig)
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve._private.replica import Request
from ray_tpu.serve._private.controller import (CONTROLLER_NAME,
                                               ServeController)

logger = logging.getLogger(__name__)

_http_proxy_info: Optional[Dict] = None


def start(detached: bool = True, http_options: Optional[Dict] = None,
          _start_proxy: bool = False):
    """Start (or connect to) the Serve instance: the controller actor and,
    optionally, the HTTP proxy."""
    controller = _get_or_create_controller()
    if _start_proxy:
        _ensure_http_proxy(controller, http_options or {})
    return controller


def _get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        pass
    cls = ray_tpu.remote(ServeController)
    controller = cls.options(
        name=CONTROLLER_NAME, lifetime="detached", num_cpus=0.1,
        max_concurrency=1000).remote()
    # Kick the reconciliation loop (runs forever inside the actor).
    controller.run_control_loop.options(num_returns=0).remote()
    return controller


_http_proxy_addrs: List[Dict] = []


def _start_one_proxy(name: str, http_options: Dict, strategy=None) -> Dict:
    from ray_tpu.serve._private.http_proxy import HTTPProxyActor
    try:
        proxy = ray_tpu.get_actor(name)
    except Exception:
        cls = ray_tpu.remote(HTTPProxyActor)
        opts = dict(name=name, lifetime="detached", num_cpus=0.1,
                    max_concurrency=1000)
        if strategy is not None:
            opts["scheduling_strategy"] = strategy
        proxy = cls.options(**opts).remote(
            http_options.get("host", "127.0.0.1"),
            http_options.get("port", 0), CONTROLLER_NAME,
            http_options.get("access_log", True))
        proxy.run.options(num_returns=0).remote()
    return ray_tpu.get(proxy.ready.remote(), timeout=60)


def _ensure_http_proxy(controller, http_options: Dict) -> Dict:
    """Start ingress: one proxy by default, or one per node with
    location="EveryNode" (reference: per-node HTTPProxyActors managed by
    http_state.py)."""
    global _http_proxy_info, _http_proxy_addrs
    if _http_proxy_info is not None:
        return _http_proxy_info
    if http_options.get("location") == "EveryNode":
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        addrs = []
        for node in ray_tpu.nodes():
            if not node.get("Alive", True):
                continue
            nid = node["NodeID"]
            addrs.append(_start_one_proxy(
                f"SERVE_PROXY::{nid[:8]}", http_options,
                NodeAffinitySchedulingStrategy(node_id=nid)))
        _http_proxy_addrs = addrs
        _http_proxy_info = addrs[0]
        return _http_proxy_info
    _http_proxy_info = _start_one_proxy("SERVE_PROXY", http_options)
    _http_proxy_addrs = [_http_proxy_info]
    return _http_proxy_info


def get_proxy_addresses() -> List[Dict]:
    """All ingress endpoints (one per node with location=EveryNode)."""
    return list(_http_proxy_addrs)


class Deployment:
    """The declarative unit: a class/function + target config.  Immutable;
    .options() returns a copy (reference: serve/deployment.py)."""

    def __init__(self, body: Union[Callable, type], name: str,
                 config: DeploymentConfig, init_args: tuple = (),
                 init_kwargs: Optional[Dict] = None,
                 ray_actor_options: Optional[Dict] = None,
                 version: Optional[str] = None,
                 route_prefix: Optional[str] = None):
        self._body = body
        self.name = name
        self.route_prefix = route_prefix
        self.config = config
        self.init_args = init_args
        self.init_kwargs = init_kwargs or {}
        self.ray_actor_options = ray_actor_options or {}
        self.version = version

    def options(self, **kwargs) -> "Deployment":
        new = Deployment(self._body, kwargs.pop("name", self.name),
                         DeploymentConfig.from_dict(self.config.to_dict()),
                         self.init_args, dict(self.init_kwargs),
                         dict(self.ray_actor_options), self.version,
                         kwargs.pop("route_prefix", self.route_prefix))
        for k in ("num_replicas", "max_concurrent_queries", "user_config",
                  "graceful_shutdown_timeout_s", "health_check_period_s",
                  "health_check_timeout_s", "drain_timeout_s"):
            if k in kwargs:
                setattr(new.config, k, kwargs.pop(k))
        if "autoscaling_config" in kwargs:
            ac = kwargs.pop("autoscaling_config")
            new.config.autoscaling_config = (
                ac if isinstance(ac, (AutoscalingConfig, type(None)))
                else AutoscalingConfig(**ac))
        if "ray_actor_options" in kwargs:
            new.ray_actor_options = kwargs.pop("ray_actor_options") or {}
        if "init_args" in kwargs:
            new.init_args = tuple(kwargs.pop("init_args"))
        if "init_kwargs" in kwargs:
            new.init_kwargs = dict(kwargs.pop("init_kwargs"))
        if "version" in kwargs:
            new.version = kwargs.pop("version")
        if kwargs:
            raise TypeError(f"unknown deployment options: {list(kwargs)}")
        return new

    def bind(self, *args, **kwargs) -> "Deployment":
        """Deployment-graph style binding of init args."""
        return self.options(init_args=args, init_kwargs=kwargs)

    def _default_version(self) -> str:
        """Content-derived version: re-deploying unchanged code is a
        reconcile no-op instead of a forced rolling restart (matters for
        composed graphs, where deploy() recurses into children)."""
        import hashlib
        try:
            blob = cloudpickle.dumps(
                (self._body, self.init_args, self.init_kwargs,
                 self.config.to_dict(), self.ray_actor_options))
            return hashlib.sha1(blob).hexdigest()[:8]
        except Exception:
            return uuid.uuid4().hex[:8]

    def deploy(self, _blocking: bool = True) -> DeploymentHandle:
        controller = _get_or_create_controller()
        version = self.version or self._default_version()
        # Model composition (reference: serve deployment graphs,
        # _private/deployment_graph_build.py:34): Deployment-typed init
        # args deploy first and arrive as handles, so an ingress class
        # can `await self.child.remote(x)` its children.
        def _resolve(v):
            if isinstance(v, Deployment):
                return v.deploy(_blocking=_blocking)
            return v

        init_args = tuple(_resolve(a) for a in self.init_args)
        init_kwargs = {k: _resolve(v) for k, v in self.init_kwargs.items()}
        rc = ReplicaConfig(
            deployment_def=cloudpickle.dumps(self._body),
            init_args=init_args, init_kwargs=init_kwargs,
            ray_actor_options=self.ray_actor_options)
        ray_tpu.get(controller.deploy.remote(
            self.name, self.config.to_dict(), rc, version,
            self.route_prefix or f"/{self.name}"), timeout=60)
        if _blocking:
            ok = ray_tpu.get(controller.wait_deployments_healthy.remote(
                [self.name]), timeout=180)
            if not ok:
                statuses = ray_tpu.get(
                    controller.get_deployment_statuses.remote(), timeout=30)
                raise RuntimeError(
                    f"deployment {self.name} failed to become healthy: "
                    f"{statuses}")
        return DeploymentHandle(self.name, controller)

    def get_handle(self) -> DeploymentHandle:
        return DeploymentHandle(self.name, _get_or_create_controller())


def deployment(_body=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 100,
               user_config: Any = None,
               autoscaling_config: Optional[Union[Dict,
                                                  AutoscalingConfig]] = None,
               ray_actor_options: Optional[Dict] = None,
               version: Optional[str] = None,
               route_prefix: Optional[str] = None,
               graceful_shutdown_timeout_s: float = 10.0,
               health_check_period_s: float = 5.0):
    """@serve.deployment decorator (reference: serve/api.py deployment)."""

    def _wrap(body):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            user_config=user_config,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            health_check_period_s=health_check_period_s)
        if autoscaling_config is not None:
            cfg.autoscaling_config = (
                autoscaling_config
                if isinstance(autoscaling_config, AutoscalingConfig)
                else AutoscalingConfig(**autoscaling_config))
        return Deployment(body, name or body.__name__, cfg,
                          ray_actor_options=ray_actor_options,
                          version=version, route_prefix=route_prefix)

    if _body is not None:
        return _wrap(_body)
    return _wrap


def run(target: Deployment, *, host: str = "127.0.0.1", port: int = 0,
        _start_proxy: bool = True) -> DeploymentHandle:
    """Deploy and (by default) expose over HTTP; returns a handle
    (reference: serve.run api.py:428)."""
    if not isinstance(target, Deployment):
        raise TypeError("serve.run expects a Deployment "
                        "(made with @serve.deployment)")
    controller = start(_start_proxy=_start_proxy,
                       http_options={"host": host, "port": port})
    return target.deploy()


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name, _get_or_create_controller())


def _deployment_from_info(info: Dict) -> Deployment:
    return Deployment(
        cloudpickle.loads(info["deployment_def"]), info["name"],
        DeploymentConfig.from_dict(info["config"]),
        init_args=tuple(info["init_args"]),
        init_kwargs=dict(info["init_kwargs"]),
        ray_actor_options=dict(info["ray_actor_options"]),
        version=info["version"], route_prefix=info["route_prefix"])


def get_deployment(name: str) -> Deployment:
    """Fetch a live deployment by name as a re-deployable Deployment
    object (reference: serve.get_deployment)."""
    controller = _get_or_create_controller()
    infos = ray_tpu.get(controller.get_deployment_info.remote(name),
                        timeout=30)
    if not infos:
        raise KeyError(f"no deployment named {name!r}")
    return _deployment_from_info(infos[0])


def list_deployments() -> Dict[str, Deployment]:
    """All live deployments, by name (reference: serve.list_deployments)."""
    controller = _get_or_create_controller()
    infos = ray_tpu.get(controller.get_deployment_info.remote(),
                        timeout=30)
    return {i["name"]: _deployment_from_info(i) for i in infos}


def build(*import_paths: str) -> Dict:
    """Emit the declarative config for deployments given by import path
    ("module:attr"), the programmatic twin of `rt serve build`
    (reference: serve.build / serve build CLI)."""
    from ray_tpu.serve.schema import build_config
    return build_config(list(import_paths))


async def _run_asgi(app, request) -> Dict:
    """Drive one request through an ASGI app (FastAPI/Starlette/raw
    callable) and capture the response as a structured dict the HTTP
    proxy unwraps (reference: serve.ingress wrapping a FastAPI app in
    the replica; here the adapter is dependency-free ASGI)."""
    from urllib.parse import urlencode

    scope = {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": request.method,
        "scheme": "http",
        "path": request.path,
        "raw_path": request.path.encode(),
        "root_path": "",
        "query_string": urlencode(request.query or {}).encode(),
        "headers": [(k.lower().encode(), v.encode())
                    for k, v in (request.headers or {}).items()],
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 0),
    }
    body = request.body or b""
    sent = {"done": False}

    async def receive():
        if sent["done"]:
            return {"type": "http.disconnect"}
        sent["done"] = True
        return {"type": "http.request", "body": body, "more_body": False}

    out = {"status": 200, "headers": [], "chunks": []}

    async def send(message):
        if message["type"] == "http.response.start":
            out["status"] = message["status"]
            out["headers"] = message.get("headers", [])
        elif message["type"] == "http.response.body":
            out["chunks"].append(message.get("body", b""))

    await app(scope, receive, send)
    # Keep headers as an ordered (name, value) pair list: collapsing to
    # a dict would drop repeats, and Set-Cookie legitimately repeats.
    headers = [(k.decode("latin-1"), v.decode("latin-1"))
               for k, v in out["headers"]]
    content_type = next((v for k, v in headers
                         if k.lower() == "content-type"), "text/plain")
    return {"__http__": True, "status": out["status"],
            "content_type": content_type,
            "headers": headers, "body": b"".join(out["chunks"])}


def ingress(app):
    """Route ALL HTTP traffic of a deployment through an ASGI app
    (reference: serve.ingress(fastapi_app)).  The decorated class's
    instance is reachable from route handlers via
    serve.get_replica_context().servable_object; direct handle calls
    (`handle.method.remote`) still hit the class's own methods."""

    def decorator(cls):
        if not inspect.isclass(cls):
            raise TypeError("@serve.ingress must decorate a class")

        class _ASGIIngress(cls):
            async def __call__(self, request):  # proxy entry point
                if not isinstance(request, Request):
                    # Plain handle call falls through to the user class.
                    parent = getattr(super(), "__call__", None)
                    if parent is None:
                        raise TypeError(
                            f"{cls.__name__} has no __call__ for "
                            "non-HTTP invocation")
                    result = parent(request)
                    if inspect.iscoroutine(result):
                        result = await result
                    return result
                return await _run_asgi(app, request)

        _ASGIIngress.__name__ = cls.__name__
        _ASGIIngress.__qualname__ = getattr(cls, "__qualname__",
                                            cls.__name__)
        return _ASGIIngress

    return decorator


def get_proxy_address() -> Optional[Dict]:
    return _http_proxy_info


def status() -> List[Dict]:
    controller = _get_or_create_controller()
    return ray_tpu.get(controller.get_deployment_statuses.remote(),
                       timeout=30)


def delete(name: str, _blocking: bool = True):
    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name), timeout=30)
    if _blocking:
        import time
        deadline = time.time() + 60
        while time.time() < deadline:
            if all(s["name"] != name for s in status()):
                return
            time.sleep(0.1)


def shutdown():
    """Tear the Serve instance down (controller + proxy + replicas)."""
    global _http_proxy_info, _http_proxy_addrs
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        _http_proxy_info = None
        return
    try:
        ray_tpu.get(controller.graceful_shutdown.remote(), timeout=60)
    except Exception:
        pass
    proxy_names = ["SERVE_PROXY"]
    try:
        proxy_names += [f"SERVE_PROXY::{n['NodeID'][:8]}"
                        for n in ray_tpu.nodes()]
    except Exception:
        pass
    for name in proxy_names:
        try:
            ray_tpu.kill(ray_tpu.get_actor(name))
        except Exception:
            pass
    try:
        ray_tpu.kill(controller)
    except Exception:
        pass
    _http_proxy_info = None
    _http_proxy_addrs = []
