"""Importable example deployments (used by REST-deploy tests/docs)."""

from ray_tpu import serve


@serve.deployment(name="rest_echo")
def rest_echo(req):
    if hasattr(req, "query"):
        return {"echo": req.query.get("msg", "")}
    return {"echo": req}
