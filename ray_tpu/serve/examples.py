"""Importable example deployments (used by REST-deploy tests/docs)."""

import os

from ray_tpu import serve


@serve.deployment(name="rest_echo")
def rest_echo(req):
    if hasattr(req, "query"):
        return {"echo": req.query.get("msg", "")}
    return {"echo": req}


@serve.deployment(name="pid_echo")
def pid_echo(req):
    """Reports its replica's pid — lets tests prove which requests hit
    restarted vs surviving replicas across config re-applies."""
    return {"pid": os.getpid()}
