"""Replica-side context (reference: python/ray/serve/context.py —
ReplicaContext + get_replica_context, set by the replica wrapper at
construction)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class ReplicaContext:
    deployment: str
    replica_tag: str
    servable_object: Optional[Any] = None


_INTERNAL_REPLICA_CONTEXT: Optional[ReplicaContext] = None


def _set_internal_replica_context(deployment: str, replica_tag: str,
                                  servable_object: Any = None) -> None:
    global _INTERNAL_REPLICA_CONTEXT
    _INTERNAL_REPLICA_CONTEXT = ReplicaContext(
        deployment=deployment, replica_tag=replica_tag,
        servable_object=servable_object)


def get_replica_context() -> ReplicaContext:
    """Inside a replica: which deployment/replica this code runs in
    (reference: serve.get_replica_context)."""
    if _INTERNAL_REPLICA_CONTEXT is None:
        raise RuntimeError(
            "get_replica_context() may only be called from inside a "
            "Serve replica (there is no replica context in this process)")
    return _INTERNAL_REPLICA_CONTEXT
