"""KV page accounting for the paged continuous-batching engine.

Host-side bookkeeping only — the device never sees these objects.  The
engine's KV memory is a pool of fixed-size pages (decode.init_paged_cache);
what lives here is who owns which page:

  * BlockAllocator — refcounted free-list over page ids.  A page is
    held by every block table that references it PLUS the radix tree if
    a prefix node points at it; it returns to the free list only when
    the last holder drops it.  Refcounts are what make prefix sharing
    safe: evicting one sharer can never free a page another request's
    attention still gathers through.
  * RadixPrefixCache — a radix/trie over token prefixes at PAGE
    granularity (SGLang's RadixAttention at block granularity, the same
    choice vLLM's prefix caching makes): each node is one FULL page of
    `page_size` prompt tokens and owns one allocator reference on the
    page holding that chunk's K/V.  match() walks the longest cached
    prefix; insert() adds nodes for pages not yet present; evict()
    drops least-recently-used LEAVES until enough pages are free
    (dropping a leaf only decrefs — sharers keep the page alive).

Single-owner discipline: every method is called from the engine's
worker thread (admission/eviction), never concurrently.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple


def _chunk_fp(parent_fp: str, key: Sequence[int]) -> str:
    """Fingerprint of one full page of tokens, chained off the parent
    page's fingerprint — so one fingerprint names an entire prefix, and
    two processes that never exchanged state agree on it.  blake2b (not
    Python hash(): that is salted per process) over little-endian token
    ids; 8-byte digests keep a whole top-K digest under ~1 KB."""
    h = hashlib.blake2b(parent_fp.encode("ascii"), digest_size=8)
    for t in key:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.hexdigest()


def prefix_fingerprints(tokens: Sequence[int], page_size: int,
                        max_depth: int) -> List[str]:
    """Fingerprints of a prompt's full-page prefixes, shallowest first:
    out[d-1] names tokens[: d * page_size].  The router computes these
    for an incoming prompt and intersects them with replicas' published
    digests; the radix cache computes the same chain incrementally at
    insert time, so equality means the replica holds that prefix."""
    out: List[str] = []
    fp = ""
    for i in range(min(max_depth, len(tokens) // page_size)):
        fp = _chunk_fp(fp, tokens[i * page_size:(i + 1) * page_size])
        out.append(fp)
    return out


class BlockAllocator:
    """Refcounted allocator over page ids [first_page, first_page+num).

    The engine reserves page id 0 as the TRASH page (inactive batch
    rows scatter their garbage writes there), so it allocates ids
    starting at 1 — hence `first_page`.
    """

    def __init__(self, num_pages: int, first_page: int = 1):
        if num_pages < 1:
            raise ValueError("num_pages must be >= 1")
        self.num_pages = num_pages
        self.first_page = first_page
        # LIFO free list: recently freed pages are re-handed first (their
        # stale K/V is overwritten before any unmasked read — see the
        # engine's no-zeroing note).
        self._free: List[int] = list(
            range(first_page + num_pages - 1, first_page - 1, -1))
        self._refs: Dict[int, int] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate n pages (refcount 1 each) or None — all or nothing,
        so a half-admitted request can never strand pages."""
        if n < 0:
            raise ValueError("n must be >= 0")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def incref(self, page: int) -> None:
        self._refs[page] += 1

    def decref(self, page: int) -> bool:
        """Drop one reference; True when this freed the page."""
        r = self._refs.get(page)
        if r is None:
            raise ValueError(f"decref of unheld page {page}")
        r -= 1
        if r == 0:
            del self._refs[page]
            self._free.append(page)
            return True
        self._refs[page] = r
        return False

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)


class _RadixNode:
    __slots__ = ("children", "page", "parent", "key", "last_used",
                 "fp", "depth")

    def __init__(self, key, page, parent):
        self.children: Dict[tuple, "_RadixNode"] = {}
        self.key = key
        self.page = page
        self.parent = parent
        self.last_used = 0
        self.fp = ""      # chained prefix fingerprint (root: "")
        self.depth = 0    # pages from root (root: 0)


class RadixPrefixCache:
    """Page-granularity prefix trie with LRU leaf eviction.

    Keys are tuples of `page_size` token ids; a path root->node spells a
    prompt prefix and node.page holds that chunk's K/V.  Only FULL pages
    are shareable — a partially filled page is private to its request
    (decode writes land in it).
    """

    def __init__(self, page_size: int, allocator: BlockAllocator,
                 digest_depth: int = 8):
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.page_size = page_size
        self._alloc = allocator
        self._root = _RadixNode(None, None, None)
        self._clock = 0
        self.nodes = 0
        # Affinity digest index: fingerprint -> node, maintained
        # incrementally for nodes at depth <= digest_depth (fingerprints
        # chain off the parent, so one entry names a whole prefix).  The
        # depth cap bounds the index — and the digest the router sees —
        # independent of how deep the trie grows.
        self.digest_depth = digest_depth
        self._fp_index: Dict[str, _RadixNode] = {}

    def match(self, tokens: Sequence[int], max_tokens: Optional[int] = None
              ) -> Tuple[List[int], int]:
        """Longest cached prefix of `tokens` in full pages.

        Returns (pages, matched_token_count).  `max_tokens` caps the
        match (the engine passes len(prompt)-1: at least one prompt
        token must run through tail prefill to produce the logits the
        first sampled token comes from — a pure cache hit yields K/V,
        never logits).  Matched nodes are touched for LRU; the CALLER
        must incref the returned pages before relying on them (a later
        evict() may drop the nodes)."""
        psz = self.page_size
        limit = len(tokens) if max_tokens is None else min(
            max_tokens, len(tokens))
        self._clock += 1
        node = self._root
        pages: List[int] = []
        for i in range(limit // psz):
            child = node.children.get(tuple(tokens[i * psz:(i + 1) * psz]))
            if child is None:
                break
            child.last_used = self._clock
            pages.append(child.page)
            node = child
        return pages, len(pages) * psz

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Record that pages[i] holds the K/V of tokens[i*psz:(i+1)*psz].

        Walks/creates the path; each NEW node increfs its page.  Where a
        node already exists (another request cached the same chunk
        first) the existing page is kept and the duplicate is ignored —
        the caller keeps its own reference on the duplicate and frees it
        with the request.  Returns the number of new nodes."""
        psz = self.page_size
        self._clock += 1
        node = self._root
        added = 0
        for i, page in enumerate(pages):
            key = tuple(tokens[i * psz:(i + 1) * psz])
            child = node.children.get(key)
            if child is None:
                child = _RadixNode(key, page, node)
                child.depth = node.depth + 1
                if child.depth <= self.digest_depth:
                    child.fp = _chunk_fp(node.fp, key)
                    self._fp_index[child.fp] = child
                node.children[key] = child
                self._alloc.incref(page)
                self.nodes += 1
                added += 1
            child.last_used = self._clock
            node = child
        return added

    def _unindex(self, node: _RadixNode) -> None:
        if node.fp and self._fp_index.get(node.fp) is node:
            del self._fp_index[node.fp]

    def digest(self, top_k: int) -> List[Dict]:
        """The replica's affinity digest: the top_k most recently used
        MAXIMAL indexed prefixes as [{"fp", "d"}].  The router scores by
        the deepest request fingerprint present in the digest, and a
        depth-d entry implies the whole d-page prefix is cached — so an
        ancestor of an advertised node carries zero information and
        advertising it would waste a top_k slot (with 8-deep chains,
        raw-node top-K covers 8x fewer distinct prefixes).  Recency
        ties break deepest-first for the same reason as hot_prefixes:
        a path touched as one unit stamps every node the same clock.
        Bounded by both top_k and digest_depth, so it stays gauge-sized
        however big the trie is."""
        return [{"fp": n.fp, "d": n.depth}
                for n in self._pick_maximal(top_k)]

    def _pick_maximal(self, top_k: int) -> List["_RadixNode"]:
        """Up to top_k indexed nodes, most recently used first, maximal
        paths only.  The forward pass skips a candidate implied by an
        ALREADY-picked descendant; the final pass drops a picked node
        whose descendant was picked LATER (an ancestor more recently
        used than its child gets selected first, and nothing in the
        forward pass revisits it) — without it the output would carry
        redundant ancestors, breaking the ancestor-deduped contract
        digest()/hot_prefixes() advertise."""
        picked: List[_RadixNode] = []
        for n in sorted(self._fp_index.values(),
                        key=lambda n: (-n.last_used, -n.depth)):
            if len(picked) >= top_k:
                break
            if any(self._is_ancestor(n, p) for p in picked):
                continue  # implied by a deeper advertised node
            picked.append(n)
        return [n for n in picked
                if not any(n is not p and self._is_ancestor(n, p)
                           for p in picked)]

    def prefix_tokens(self, node: _RadixNode) -> List[int]:
        out: List[int] = []
        while node is not self._root and node is not None:
            out[:0] = node.key
            node = node.parent
        return out

    def hot_prefixes(self, top_k: int) -> List[List[int]]:
        """Token sequences of the hottest cached prefixes, maximal
        paths only (a selected node's ancestors are implied — the
        destination's longest-prefix match recovers them for free).
        Drain migration walks these to re-home still-referenced pages
        before teardown."""
        return [self.prefix_tokens(n)
                for n in self._pick_maximal(top_k)]

    @staticmethod
    def _is_ancestor(a: _RadixNode, b: _RadixNode) -> bool:
        while b is not None:
            if b is a:
                return True
            b = b.parent
        return False

    def releasable(self) -> int:
        """Pages the tree could actually FREE by evicting everything:
        nodes whose page has no holder besides the tree itself.  The
        engine checks this before evicting — when even a full wipe
        cannot cover a reservation, destroying the cache buys nothing
        (the request waits for residents to finish instead, and future
        prefix hits survive)."""
        count, stack = 0, list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if self._alloc.refcount(n.page) == 1:
                count += 1
        return count

    def _leaves(self) -> List[_RadixNode]:
        out, stack = [], list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out

    def evict(self, need_free: int) -> int:
        """Drop LRU leaves until the allocator has `need_free` free
        pages or nothing is evictable.  Dropping a leaf decrefs its
        page — shared pages survive until their sharers finish.  Returns
        the number of nodes dropped.

        One DFS seeds a heap of leaves; a drop that exposes its parent
        pushes the parent, so a whole cold branch unwinds in O(log n)
        per node instead of rescanning the trie per freed page.  A
        parent touched AFTER its leaf (heap entries are stale snapshots)
        re-enters the heap with its CURRENT last_used, so recency is
        honored at pop time."""
        import heapq
        if self._alloc.free_pages >= need_free:
            return 0
        heap = [(n.last_used, i, n)
                for i, n in enumerate(self._leaves())]
        heapq.heapify(heap)
        tick = len(heap)
        dropped = 0
        while self._alloc.free_pages < need_free and heap:
            seen, _, victim = heapq.heappop(heap)
            if victim.children \
                    or victim.parent.children.get(victim.key) is not victim:
                continue  # stale entry (no longer a leaf / already gone)
            if victim.last_used != seen:
                tick += 1
                heapq.heappush(heap, (victim.last_used, tick, victim))
                continue  # touched since snapshot: re-sort by recency
            parent = victim.parent
            del parent.children[victim.key]
            self._unindex(victim)
            self._alloc.decref(victim.page)
            self.nodes -= 1
            dropped += 1
            if parent is not self._root and not parent.children:
                tick += 1
                heapq.heappush(heap, (parent.last_used, tick, parent))
        return dropped

    def clear(self) -> None:
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self._alloc.decref(node.page)
        self._root.children.clear()
        self._fp_index.clear()
        self.nodes = 0
