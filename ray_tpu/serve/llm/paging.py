"""KV page accounting for the paged continuous-batching engine.

Host-side bookkeeping only — the device never sees these objects.  The
engine's KV memory is a pool of fixed-size pages (decode.init_paged_cache);
what lives here is who owns which page:

  * BlockAllocator — refcounted free-list over page ids.  A page is
    held by every block table that references it PLUS the radix tree if
    a prefix node points at it; it returns to the free list only when
    the last holder drops it.  Refcounts are what make prefix sharing
    safe: evicting one sharer can never free a page another request's
    attention still gathers through.
  * RadixPrefixCache — a radix/trie over token prefixes at PAGE
    granularity (SGLang's RadixAttention at block granularity, the same
    choice vLLM's prefix caching makes): each node is one FULL page of
    `page_size` prompt tokens and owns one allocator reference on the
    page holding that chunk's K/V.  match() walks the longest cached
    prefix; insert() adds nodes for pages not yet present; evict()
    drops least-recently-used LEAVES until enough pages are free
    (dropping a leaf only decrefs — sharers keep the page alive).

Single-owner discipline: every method is called from the engine's
worker thread (admission/eviction), never concurrently.
"""

from __future__ import annotations

import hashlib
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Tier ids for _RadixNode.tier: the decode pool (pages live on device,
# node.page is a pool page id), the host shared-memory arena, and the
# file-backed page store.  A node's K/V bytes live in EXACTLY one tier;
# demotion/promotion moves them, never copies them live in two places
# (the store is the exception by design: a T2 entry persists on disk
# even after its node is promoted or evicted — that persistence IS the
# durability the session-resurrect path relies on).
TIER_POOL = 0
TIER_HOST = 1
TIER_STORE = 2


def _chunk_fp(parent_fp: str, key: Sequence[int]) -> str:
    """Fingerprint of one full page of tokens, chained off the parent
    page's fingerprint — so one fingerprint names an entire prefix, and
    two processes that never exchanged state agree on it.  blake2b (not
    Python hash(): that is salted per process) over little-endian token
    ids; 8-byte digests keep a whole top-K digest under ~1 KB."""
    h = hashlib.blake2b(parent_fp.encode("ascii"), digest_size=8)
    for t in key:
        h.update(int(t).to_bytes(8, "little", signed=True))
    return h.hexdigest()


def prefix_fingerprints(tokens: Sequence[int], page_size: int,
                        max_depth: int) -> List[str]:
    """Fingerprints of a prompt's full-page prefixes, shallowest first:
    out[d-1] names tokens[: d * page_size].  The router computes these
    for an incoming prompt and intersects them with replicas' published
    digests; the radix cache computes the same chain incrementally at
    insert time, so equality means the replica holds that prefix."""
    out: List[str] = []
    fp = ""
    for i in range(min(max_depth, len(tokens) // page_size)):
        fp = _chunk_fp(fp, tokens[i * page_size:(i + 1) * page_size])
        out.append(fp)
    return out


class BlockAllocator:
    """Refcounted allocator over page ids [first_page, first_page+num).

    The engine reserves page id 0 as the TRASH page (inactive batch
    rows scatter their garbage writes there), so it allocates ids
    starting at 1 — hence `first_page`.
    """

    def __init__(self, num_pages: int, first_page: int = 1):
        if num_pages < 1:
            raise ValueError("num_pages must be >= 1")
        self.num_pages = num_pages
        self.first_page = first_page
        # LIFO free list: recently freed pages are re-handed first (their
        # stale K/V is overwritten before any unmasked read — see the
        # engine's no-zeroing note).
        self._free: List[int] = list(
            range(first_page + num_pages - 1, first_page - 1, -1))
        self._refs: Dict[int, int] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate n pages (refcount 1 each) or None — all or nothing,
        so a half-admitted request can never strand pages."""
        if n < 0:
            raise ValueError("n must be >= 0")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def incref(self, page: int) -> None:
        self._refs[page] += 1

    def decref(self, page: int) -> bool:
        """Drop one reference; True when this freed the page."""
        r = self._refs.get(page)
        if r is None:
            raise ValueError(f"decref of unheld page {page}")
        r -= 1
        if r == 0:
            del self._refs[page]
            self._free.append(page)
            return True
        self._refs[page] = r
        return False

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)


class _RadixNode:
    __slots__ = ("children", "page", "parent", "key", "last_used",
                 "fp", "depth", "tier", "payload", "last_used_t")

    def __init__(self, key, page, parent):
        self.children: Dict[tuple, "_RadixNode"] = {}
        self.key = key
        self.page = page
        self.parent = parent
        self.last_used = 0
        self.fp = ""      # chained prefix fingerprint (root: "")
        self.depth = 0    # pages from root (root: 0)
        # Tier state: TIER_POOL means `page` is a live pool page id;
        # TIER_HOST/TIER_STORE mean `page` is None and `payload` names
        # where the bytes went — ("t1", slot, crc, nbytes) for an arena
        # slot, ("t2", key, crc, nbytes) for a store entry.  last_used_t
        # is the wall-clock twin of the LRU logical clock; the demotion
        # sweeper compares it against the idle knobs.
        self.tier = TIER_POOL
        self.payload: Optional[tuple] = None
        self.last_used_t = 0.0


class RadixPrefixCache:
    """Page-granularity prefix trie with LRU leaf eviction.

    Keys are tuples of `page_size` token ids; a path root->node spells a
    prompt prefix and node.page holds that chunk's K/V.  Only FULL pages
    are shareable — a partially filled page is private to its request
    (decode writes land in it).
    """

    def __init__(self, page_size: int, allocator: BlockAllocator,
                 digest_depth: int = 8):
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.page_size = page_size
        self._alloc = allocator
        self._root = _RadixNode(None, None, None)
        self._clock = 0
        self.nodes = 0
        # Affinity digest index: fingerprint -> node, maintained
        # incrementally for nodes at depth <= digest_depth (fingerprints
        # chain off the parent, so one entry names a whole prefix).  The
        # depth cap bounds the index — and the digest the router sees —
        # independent of how deep the trie grows.
        self.digest_depth = digest_depth
        self._fp_index: Dict[str, _RadixNode] = {}
        # Nodes per tier, maintained incrementally (load_info polls
        # this every autoscale tick — never a tree walk on that path).
        self.tier_nodes: List[int] = [0, 0, 0]
        # Called with a node's payload whenever the tree stops owning
        # it (promotion, adoption by insert, eviction, clear).  The
        # engine points this at the arena's slot-free; T2 payloads are
        # deliberately NOT deleted from the store here (persistence is
        # the point — the store's TTL sweep owns their lifetime).
        self.release_payload: Optional[Callable[[tuple], None]] = None

    def _drop_payload(self, node: _RadixNode) -> None:
        if node.payload is not None and self.release_payload is not None:
            try:
                self.release_payload(node.payload)
            except Exception:
                pass  # a leaked arena slot must never poison the trie
        node.payload = None

    def match(self, tokens: Sequence[int], max_tokens: Optional[int] = None
              ) -> Tuple[List[int], int]:
        """Longest cached POOL-TIER prefix of `tokens` in full pages.

        Returns (pages, matched_token_count).  `max_tokens` caps the
        match (the engine passes len(prompt)-1: at least one prompt
        token must run through tail prefill to produce the logits the
        first sampled token comes from — a pure cache hit yields K/V,
        never logits).  The walk stops at the first demoted node: a
        T1/T2 node has no pool page to hand out — callers that can
        promote use match_nodes() instead.  Matched nodes are touched
        for LRU; the CALLER must incref the returned pages before
        relying on them (a later evict() may drop the nodes)."""
        nodes, _ = self.match_nodes(tokens, max_tokens)
        pages: List[int] = []
        for n in nodes:
            if n.tier != TIER_POOL:
                break
            pages.append(n.page)
        return pages, len(pages) * self.page_size

    def match_nodes(self, tokens: Sequence[int],
                    max_tokens: Optional[int] = None
                    ) -> Tuple[List["_RadixNode"], int]:
        """Longest cached prefix of `tokens` as the NODE path, any
        tier.  The engine's reservation path walks this to promote
        demoted nodes back into the pool in the same all-or-nothing
        reservation that admits the request.  Touches LRU (logical
        clock and wall time) for every matched node."""
        psz = self.page_size
        limit = len(tokens) if max_tokens is None else min(
            max_tokens, len(tokens))
        self._clock += 1
        now = time.monotonic()
        node = self._root
        out: List[_RadixNode] = []
        for i in range(limit // psz):
            child = node.children.get(tuple(tokens[i * psz:(i + 1) * psz]))
            if child is None:
                break
            child.last_used = self._clock
            child.last_used_t = now
            out.append(child)
            node = child
        return out, len(out) * psz

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Record that pages[i] holds the K/V of tokens[i*psz:(i+1)*psz].

        Walks/creates the path; each NEW node increfs its page.  Where a
        node already exists (another request cached the same chunk
        first) the existing page is kept and the duplicate is ignored —
        the caller keeps its own reference on the duplicate and frees it
        with the request.  A DEMOTED node on the path instead ADOPTS the
        caller's page (incref'd for the tree, old payload released):
        the caller just computed or imported bit-identical K/V for that
        chunk, so this is a free promotion.  Returns the number of new
        nodes."""
        psz = self.page_size
        self._clock += 1
        now = time.monotonic()
        node = self._root
        added = 0
        for i, page in enumerate(pages):
            key = tuple(tokens[i * psz:(i + 1) * psz])
            child = node.children.get(key)
            if child is None:
                if page is None:
                    # A placeholder for a path node that vanished
                    # between the caller's match and this insert; a
                    # node cannot exist without bytes, so the rest of
                    # the path is unpublishable too.
                    break
                child = _RadixNode(key, page, node)
                child.depth = node.depth + 1
                if child.depth <= self.digest_depth:
                    child.fp = _chunk_fp(node.fp, key)
                    self._fp_index[child.fp] = child
                node.children[key] = child
                self._alloc.incref(page)
                self.nodes += 1
                self.tier_nodes[TIER_POOL] += 1
                added += 1
            elif child.tier != TIER_POOL and page is not None:
                # Adoption: deterministic prefill/import reproduced this
                # chunk's K/V bit-identically in the caller's page.  A
                # None page means the caller is extending BELOW a
                # demoted ancestor without re-materializing it (store
                # import); the ancestor keeps its tier payload.
                self.tier_nodes[child.tier] -= 1
                self.tier_nodes[TIER_POOL] += 1
                child.tier = TIER_POOL
                child.page = page
                self._drop_payload(child)
                self._alloc.incref(page)
            child.last_used = self._clock
            child.last_used_t = now
            node = child
        return added

    # -- tier transitions (engine worker thread only) -------------------

    def path_fp(self, node: _RadixNode) -> str:
        """Full-depth chained fingerprint of the prefix this node caps
        (the digest index only carries fingerprints to digest_depth;
        store-tier keys need them at ANY depth, so this recomputes the
        chain from the root — O(depth), demotion-path only)."""
        keys: List[tuple] = []
        n = node
        while n is not self._root and n is not None:
            keys.append(n.key)
            n = n.parent
        fp = ""
        for key in reversed(keys):
            fp = _chunk_fp(fp, key)
        return fp

    def demote_candidates(self, min_idle_s: float,
                          tier: int = TIER_POOL,
                          limit: Optional[int] = None
                          ) -> List["_RadixNode"]:
        """Nodes eligible to leave `tier`, coldest first.  T0 eligibility
        is tree-only pages (refcount 1 — a page a live request still
        gathers through is NEVER demoted) idle at least min_idle_s; T1
        eligibility is idle time alone.  min_idle_s=0 is the pressure
        path: anything tree-only is fair game, LRU order."""
        now = time.monotonic()
        out: List[_RadixNode] = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.tier != tier:
                continue
            if tier == TIER_POOL and self._alloc.refcount(n.page) != 1:
                continue
            if now - n.last_used_t < min_idle_s:
                continue
            out.append(n)
        out.sort(key=lambda n: (n.last_used, -n.depth))
        return out if limit is None else out[:limit]

    def apply_demote(self, node: _RadixNode, tier: int,
                     payload: tuple) -> None:
        """Commit one node's demotion AFTER its bytes landed in the
        destination tier: the pool page is freed (T0 source; caller
        guaranteed refcount 1) or the arena slot released (T1 source),
        and the node now names `payload` instead."""
        if node.tier == TIER_POOL:
            self._alloc.decref(node.page)
            node.page = None
        else:
            self._drop_payload(node)
        self.tier_nodes[node.tier] -= 1
        self.tier_nodes[tier] += 1
        node.tier = tier
        node.payload = payload

    def promote(self, node: _RadixNode, page: int) -> None:
        """Commit one node's promotion AFTER its bytes landed in pool
        page `page` (freshly alloc'd — its allocation ref becomes the
        tree's ref, mirroring insert()'s accounting)."""
        self._drop_payload(node)
        self.tier_nodes[node.tier] -= 1
        self.tier_nodes[TIER_POOL] += 1
        node.tier = TIER_POOL
        node.page = page

    def _unindex(self, node: _RadixNode) -> None:
        if node.fp and self._fp_index.get(node.fp) is node:
            del self._fp_index[node.fp]

    def digest(self, top_k: int) -> List[Dict]:
        """The replica's affinity digest: the top_k most recently used
        MAXIMAL indexed prefixes as [{"fp", "d"}].  The router scores by
        the deepest request fingerprint present in the digest, and a
        depth-d entry implies the whole d-page prefix is cached — so an
        ancestor of an advertised node carries zero information and
        advertising it would waste a top_k slot (with 8-deep chains,
        raw-node top-K covers 8x fewer distinct prefixes).  Recency
        ties break deepest-first for the same reason as hot_prefixes:
        a path touched as one unit stamps every node the same clock.
        Bounded by both top_k and digest_depth, so it stays gauge-sized
        however big the trie is.  Each entry carries "t": the WORST
        tier on its root path — the router discounts T1/T2 hits against
        T0 hits (a promoted page costs a host->device splice a pool hit
        does not)."""
        return [{"fp": n.fp, "d": n.depth, "t": self._path_tier(n)}
                for n in self._pick_maximal(top_k)]

    def _path_tier(self, node: _RadixNode) -> int:
        worst = node.tier
        n = node.parent
        while n is not None and n.parent is not None:
            if n.tier > worst:
                worst = n.tier
            n = n.parent
        return worst

    def _pick_maximal(self, top_k: int) -> List["_RadixNode"]:
        """Up to top_k indexed nodes, most recently used first, maximal
        paths only.  The forward pass skips a candidate implied by an
        ALREADY-picked descendant; the final pass drops a picked node
        whose descendant was picked LATER (an ancestor more recently
        used than its child gets selected first, and nothing in the
        forward pass revisits it) — without it the output would carry
        redundant ancestors, breaking the ancestor-deduped contract
        digest()/hot_prefixes() advertise."""
        picked: List[_RadixNode] = []
        for n in sorted(self._fp_index.values(),
                        key=lambda n: (-n.last_used, -n.depth)):
            if len(picked) >= top_k:
                break
            if any(self._is_ancestor(n, p) for p in picked):
                continue  # implied by a deeper advertised node
            picked.append(n)
        return [n for n in picked
                if not any(n is not p and self._is_ancestor(n, p)
                           for p in picked)]

    def prefix_tokens(self, node: _RadixNode) -> List[int]:
        out: List[int] = []
        while node is not self._root and node is not None:
            out[:0] = node.key
            node = node.parent
        return out

    def hot_prefixes(self, top_k: int) -> List[List[int]]:
        """Token sequences of the hottest cached prefixes, maximal
        paths only (a selected node's ancestors are implied — the
        destination's longest-prefix match recovers them for free).
        Drain migration walks these to re-home still-referenced pages
        before teardown."""
        return [self.prefix_tokens(n)
                for n in self._pick_maximal(top_k)]

    @staticmethod
    def _is_ancestor(a: _RadixNode, b: _RadixNode) -> bool:
        while b is not None:
            if b is a:
                return True
            b = b.parent
        return False

    def releasable(self) -> int:
        """POOL pages the tree could actually FREE by evicting
        everything: T0 nodes whose page has no holder besides the tree
        itself.  Tier-aware on purpose — a demoted node holds no pool
        page, so counting it would overstate what eviction can reclaim
        and let an unsatisfiable reservation wipe the cache for
        nothing.  The engine checks this before evicting; when even a
        full wipe cannot cover a reservation, the request waits for
        residents to finish instead and future prefix hits survive."""
        count, stack = 0, list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.tier == TIER_POOL \
                    and self._alloc.refcount(n.page) == 1:
                count += 1
        return count

    def _leaves(self) -> List[_RadixNode]:
        out, stack = [], list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            else:
                out.append(n)
        return out

    def evict(self, need_free: int) -> int:
        """Drop LRU leaves until the allocator has `need_free` free
        pages or nothing is evictable.  Dropping a leaf decrefs its
        page — shared pages survive until their sharers finish.  Returns
        the number of nodes dropped.

        One DFS seeds a heap of leaves; a drop that exposes its parent
        pushes the parent, so a whole cold branch unwinds in O(log n)
        per node instead of rescanning the trie per freed page.  A
        parent touched AFTER its leaf (heap entries are stale snapshots)
        re-enters the heap with its CURRENT last_used, so recency is
        honored at pop time."""
        import heapq
        if self._alloc.free_pages >= need_free:
            return 0
        heap = [(n.last_used, i, n)
                for i, n in enumerate(self._leaves())]
        heapq.heapify(heap)
        tick = len(heap)
        dropped = 0
        while self._alloc.free_pages < need_free and heap:
            seen, _, victim = heapq.heappop(heap)
            if victim.children \
                    or victim.parent.children.get(victim.key) is not victim:
                continue  # stale entry (no longer a leaf / already gone)
            if victim.last_used != seen:
                tick += 1
                heapq.heappush(heap, (victim.last_used, tick, victim))
                continue  # touched since snapshot: re-sort by recency
            parent = victim.parent
            del parent.children[victim.key]
            self._unindex(victim)
            if victim.tier == TIER_POOL:
                self._alloc.decref(victim.page)
            else:
                # A demoted leaf frees no pool page, but dropping it
                # exposes its (warmer, possibly T0) parent to the heap.
                # Its T2 copy persists in the store; a T1 payload's
                # arena slot is handed back through the release hook.
                self._drop_payload(victim)
            self.tier_nodes[victim.tier] -= 1
            self.nodes -= 1
            dropped += 1
            if parent is not self._root and not parent.children:
                tick += 1
                heapq.heappush(heap, (parent.last_used, tick, parent))
        return dropped

    def clear(self) -> None:
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.tier == TIER_POOL:
                self._alloc.decref(node.page)
            else:
                self._drop_payload(node)
        self._root.children.clear()
        self._fp_index.clear()
        self.nodes = 0
        self.tier_nodes = [0, 0, 0]
