"""Admission control for the continuous-batching engine.

FCFS (Orca's baseline policy): requests join a bounded queue and enter
the slot pool strictly in arrival order — no reordering, so a request's
TTFT is bounded by the work ahead of it, never by work behind it.  The
queue depth cap is the backpressure surface: past it, submit() fails
fast with EngineOverloadedError instead of buffering unboundedly inside
the replica (the router/autoscaler see the error and route or scale).
"""

from __future__ import annotations

import collections
from typing import Deque, Optional


class EngineOverloadedError(RuntimeError):
    """Admission is saturated; retry later or scale out.

    Structured: `reason` distinguishes WHICH resource saturated —
    "queue_full" (the waiting line hit max_queue_len; drains at
    admission speed, retry soon) vs "kv_exhausted" (outstanding
    worst-case KV page demand passed the commit cap; drains at
    GENERATION speed, retry later) — and `retry_after_s` is the
    matching client hint (serve surfaces it as HTTP 503 +
    Retry-After).  Deliberately a RuntimeError subclass so generic
    handlers keep working."""

    def __init__(self, message: str, *, reason: str = "queue_full",
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


class FCFSScheduler:
    """Bounded first-come-first-served admission queue.

    Single-owner discipline: enqueue() is called from submitter tasks
    (under the engine's lock), next_request()/requeue_head() only from
    the engine's worker thread.  Depth counts WAITING requests only;
    the engine adds the one mid-prefill when it reports stats.
    """

    def __init__(self, max_queue_len: int = 64):
        if max_queue_len < 1:
            raise ValueError("max_queue_len must be >= 1")
        self.max_queue_len = max_queue_len
        self._queue: Deque = collections.deque()

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def depth(self) -> int:
        return len(self._queue)

    def enqueue(self, request) -> None:
        """Admit to the wait queue or raise EngineOverloadedError."""
        if len(self._queue) >= self.max_queue_len:
            raise EngineOverloadedError(
                f"admission queue full ({len(self._queue)}/"
                f"{self.max_queue_len} requests waiting); retry later",
                reason="queue_full", retry_after_s=1.0)
        self._queue.append(request)

    def next_request(self) -> Optional[object]:
        """Pop the oldest waiting request (None when empty)."""
        if not self._queue:
            return None
        return self._queue.popleft()

    def requeue_head(self, request) -> None:
        """Put a request back at the FRONT (admission aborted — e.g. the
        engine is stopping mid-prefill); preserves FCFS order."""
        self._queue.appendleft(request)

    def drain(self):
        """Remove and return every waiting request (engine shutdown)."""
        out = list(self._queue)
        self._queue.clear()
        return out
