"""ray_tpu.serve.llm: continuous-batching LLM inference.

Iteration-level scheduling (Orca) over the static-shape KV caches of
models/decode.py: a fixed pool of cache slots, chunked prefill so
admission never stalls decoding for more than one chunk, one fused
decode_step per tick across every occupied slot, and per-request token
streams.  vLLM's slot-recycling insight without paging — TPU-native
static shapes make whole-slot recycling the natural unit.

    engine.py     GenerationEngine + TokenStream (the device loop)
    scheduler.py  FCFS admission queue with backpressure
    api.py        LLMServer deployment: generate()/stream()/HTTP+SSE
"""

from ray_tpu.serve.llm.engine import (  # noqa: F401
    EngineStats,
    GenerationEngine,
    TokenStream,
)
from ray_tpu.serve.llm.scheduler import (  # noqa: F401
    EngineOverloadedError,
    FCFSScheduler,
)
from ray_tpu.serve.llm.api import LLMServer, llm_deployment  # noqa: F401

__all__ = [
    "EngineOverloadedError", "EngineStats", "FCFSScheduler",
    "GenerationEngine", "LLMServer", "TokenStream", "llm_deployment",
]
