"""ray_tpu.serve.llm: continuous-batching LLM inference.

Iteration-level scheduling (Orca) over a PAGED KV cache
(vLLM's PagedAttention expressed in models/decode.py's masked
static-shape style): requests reserve fixed-size pages from a shared
pool and address them through per-row block tables, a radix prefix
cache (SGLang's RadixAttention at page granularity) shares full prompt
pages between requests so repeated system prompts prefill once, and
prompt-lookup speculative decoding is fused into the batched tick —
greedy rows verify their drafts in the same dispatch every other row
decodes in.  Admission is free-page-bounded, chunked prefill never
stalls decoding for more than one chunk, and with temperature=0 every
request's tokens are bit-identical to decode.generate() run alone.

    engine.py      GenerationEngine + TokenStream (the device loop)
    paging.py      BlockAllocator + RadixPrefixCache (page bookkeeping)
    scheduler.py   FCFS admission queue with structured backpressure
    api.py         LLMServer deployment: generate()/stream()/HTTP+SSE
    kv_transfer.py live KV-page migration over the transfer plane
"""

from ray_tpu.serve.llm.engine import (  # noqa: F401
    EngineStats,
    GenerationEngine,
    TokenStream,
)
from ray_tpu.serve.llm.paging import (  # noqa: F401
    BlockAllocator,
    RadixPrefixCache,
)
from ray_tpu.serve.llm.scheduler import (  # noqa: F401
    EngineOverloadedError,
    FCFSScheduler,
)
from ray_tpu.serve.llm.api import LLMServer, llm_deployment  # noqa: F401
from ray_tpu.serve.llm import kv_transfer  # noqa: F401

__all__ = [
    "BlockAllocator", "EngineOverloadedError", "EngineStats",
    "FCFSScheduler", "GenerationEngine", "LLMServer",
    "RadixPrefixCache", "TokenStream", "kv_transfer", "llm_deployment",
]
