"""Live KV-page migration: ship committed pages replica-to-replica.

A failover or drain used to re-prefill every token the origin replica
had already computed; this module moves the K/V pages themselves over
the transfer plane instead.  The wire discipline is TransferManager's
(PR 4), applied to engine pages:

  * the DESTINATION drives the pull: one `kv_export_begin` RPC makes
    the origin snapshot the longest cached full-page prefix of the
    request's tokens (pages pinned with an allocator incref — an
    eviction racing the migration can drop radix nodes but never
    recycle a page mid-wire), then page frames ride raw KIND_BLOB_REP
    replies straight into the destination's staging buffer through a
    `run_windowed` pump;
  * per-page integrity: a generation token minted at export (a reply
    from a stale or recycled export can never land), the transport's
    byte-length check, and a per-page CRC verified before anything
    touches the device;
  * same-host replicas skip the socket: the origin stages the export
    in a /dev/shm file the destination reads directly (the arena-mmap
    pattern), falling back to wire frames when the file is not
    reachable;
  * the destination lands pages into freshly reserved pool pages
    (engine.kv_import — a worker-thread command, so the splice happens
    between ticks, never stalling one) and only then `kv_export_end`s;
    the origin's pins release strictly after the destination sealed.

Failure semantics: any error on either side aborts the import whole —
the destination releases its reservation and re-prefills, the origin
keeps its pages (the radix tree still owns them), and the TTL sweep
reclaims export pins whose puller died.  A migrated stream is
bit-identical to an unmigrated one: pages are verbatim copies and the
resume path re-enters chunked prefill for whatever was not shipped.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
import uuid
import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from ray_tpu._private import failpoints
from ray_tpu._private import protocol
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import GLOBAL_CONFIG as _cfg
from ray_tpu._private.transfer import run_windowed
from ray_tpu.serve.llm.kv_tier import frame_crc, page_frame
from ray_tpu.util import metrics as _metrics

logger = logging.getLogger(__name__)

PAGES_MIGRATED_COUNTER = _metrics.Counter(
    "serve_kv_pages_migrated_total",
    "KV pages imported from another replica (committed to the pool)",
    tag_keys=("engine",))
MIGRATION_SECONDS = _metrics.Histogram(
    "serve_kv_migration_seconds",
    "Wall time of one KV migration pull, rendezvous to commit",
    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30],
    tag_keys=("engine", "outcome"))

# Engine name -> engine, for inbound export requests on this process's
# core worker (two engines in one test process keep distinct names).
_SERVICES: Dict[str, "GenerationEngineRef"] = {}
GenerationEngineRef = object  # typing alias; values are engines
# xid -> export state staged by kv_export_begin.
_EXPORTS: Dict[str, Dict] = {}
# TTL sweeper task for _EXPORTS, on the core worker's event loop.
_SWEEPER: Optional["asyncio.Task"] = None
_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else None


def _shm_path(xid: str) -> Optional[str]:
    return None if _SHM_DIR is None else \
        os.path.join(_SHM_DIR, f"rt_kvx_{xid}")


async def _on_worker(engine, fn, timeout: float = 30.0):
    """An engine worker command from this process's event loop: the
    command queue hands fn to the tick thread; run_in_executor keeps
    the blocking wait off the loop."""
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(
        None, lambda: engine.run_on_worker(fn, timeout=timeout))


def _ensure_sweeper() -> None:
    """Start the export-TTL sweeper on the running loop if it is not
    already alive.  A periodic task (not an inbound-traffic hook): a
    puller that dies and never triggers another kv_export_begin here
    must still have its orphaned export reclaimed — pinned pages,
    frames copy, and /dev/shm staging file all leak otherwise."""
    global _SWEEPER
    if _SWEEPER is None or _SWEEPER.done():
        _SWEEPER = asyncio.get_running_loop().create_task(_sweep_loop())


async def _sweep_loop() -> None:
    global _SWEEPER
    while True:
        await asyncio.sleep(max(0.5, _cfg.serve_kv_export_ttl_s / 4))
        now = time.monotonic()
        ttl = _cfg.serve_kv_export_ttl_s
        for xid in [x for x, e in _EXPORTS.items()
                    if now - e["t"] > ttl]:
            logger.warning("kv export %s never sealed; releasing", xid)
            await _release_export(xid)
        if not _EXPORTS:
            # Idle: retire (no awaits between the check and the reset,
            # so an export registered after this point sees a done/None
            # sweeper and starts a fresh one).
            _SWEEPER = None
            return


async def _release_export(xid: str) -> None:
    exp = _EXPORTS.pop(xid, None)
    if exp is None:
        return
    path = exp.get("path")
    if path:
        try:
            os.unlink(path)
        except OSError:
            pass
    engine = exp["engine"]
    try:
        # _on_worker, never a bare run_on_worker: this runs on the core
        # worker's RPC event loop, and the blocking wait for the tick
        # thread (a long decode tick, a first-time jit) must not stall
        # every other RPC and heartbeat behind it.
        await _on_worker(engine,
                         lambda: engine.kv_export_release(exp["pages"]))
    except Exception:
        logger.exception("kv export %s release failed", xid)


# ---------------------------------------------------------------- origin

async def _rpc_export_begin(conn, body):
    engine = _SERVICES.get(body.get("engine", ""))
    if engine is None:
        return {"error": f"no kv engine {body.get('engine')!r} here"}
    tokens = body["tokens"]
    try:
        exp = await _on_worker(engine,
                               lambda: engine.kv_export(tokens))
    except Exception as e:
        return {"error": f"export failed: {e!r}"}
    # Size the crossover on MATCHED pages (len(k)): with tiering, an
    # export can cover demoted pages that carry no pool pin, so
    # exp["pages"] undercounts what the wire would actually save.
    if exp is None or len(exp["k"]) < _cfg.serve_kv_min_migrate_pages:
        # Below the crossover the rendezvous costs more than the
        # prefill it would save: tell the puller to re-prefill.
        if exp is not None:
            await _on_worker(
                engine,
                lambda: engine.kv_export_release(exp["pages"]))
        return {"n": 0}
    k, v = exp["k"], exp["v"]
    # Same framing the tier hierarchy stores at rest (kv_tier): K bytes
    # then V bytes per page, CRC32 over the frame.
    frames = [page_frame(k[i], v[i]) for i in range(len(k))]
    xid = uuid.uuid4().hex[:12]
    gen = uuid.uuid4().hex[:12]
    path = None
    if _cfg.serve_kv_samehost:
        path = _shm_path(xid)
        if path is not None:
            try:
                with open(path, "wb") as f:
                    for fr in frames:
                        f.write(fr)
            except OSError:
                path = None
    _EXPORTS[xid] = {"engine": engine, "pages": exp["pages"],
                     "frames": frames, "gen": gen, "path": path,
                     "t": time.monotonic()}
    _ensure_sweeper()
    return {"xid": xid, "gen": gen, "n": len(frames),
            "matched_tokens": exp["matched_tokens"],
            "page_nbytes": len(frames[0]), "k_nbytes": k[0].nbytes,
            "shape_k": tuple(k.shape[1:]), "shape_v": tuple(v.shape[1:]),
            "dtype": str(k.dtype), "crc": [frame_crc(f) for f in frames],
            "path": path}


async def _rpc_fetch_page(conn, body):
    if failpoints.ACTIVE:
        act = failpoints.check("serve.kv_fetch_page")
        if act is not None:
            if act.kind == "error":
                return {"error": "failpoint: injected kv fetch error"}
            if act.kind == "delay":
                await asyncio.sleep(act.delay_s)
    exp = _EXPORTS.get(body.get("xid"))
    if exp is None or exp["gen"] != body.get("gen"):
        # Stale/recycled export: the generation check is what keeps a
        # late frame from sealing garbage into a NEW migration's pages.
        return {"error": "unknown or stale kv export"}
    # A live pull keeps its export alive: without the refresh a slow
    # (or failpoint-delayed) window could cross the TTL and get swept
    # mid-pull, failing a healthy migration into re-prefill.
    exp["t"] = time.monotonic()
    i = body["i"]
    if not 0 <= i < len(exp["frames"]):
        return {"error": f"page index {i} out of range"}
    frame = exp["frames"][i]
    return protocol.Blob({"len": len(frame), "gen": exp["gen"]},
                         memoryview(frame))


async def _rpc_export_end(conn, body):
    await _release_export(body.get("xid"))
    return {"ok": True}


def serve_exports(engine) -> None:
    """Register `engine` as an export source on this process's core
    worker (idempotent).  Handlers are process-global; the engine name
    in each request routes to the right engine."""
    _SERVICES[engine.name] = engine
    try:
        from ray_tpu._private.worker import global_worker as w
    except Exception:
        return
    if "kv_export_begin" not in w.ext_rpc:
        w.ext_rpc["kv_export_begin"] = _rpc_export_begin
        w.ext_rpc["kv_fetch_page"] = _rpc_fetch_page
        w.ext_rpc["kv_export_end"] = _rpc_export_end


def rendezvous(engine) -> Optional[Dict]:
    """This replica's pull address: (host, port) of its core worker's
    RPC server plus the engine name.  Rides load gauges and resume
    cursors so a peer (or the router) can point a migration here.
    None outside a cluster (no worker server to pull from)."""
    serve_exports(engine)
    try:
        from ray_tpu._private.worker import global_worker as w
        addr = w.addr
    except Exception:
        return None
    if addr is None:
        return None
    return {"host": addr[0], "port": int(addr[1]),
            "engine": engine.name}


# ----------------------------------------------------------- destination

async def pull_kv_pages(rdv: Dict, tokens: Sequence[int], engine,
                        timeout: float = 30.0) -> int:
    """Pull the K/V pages an origin replica holds for `tokens` into
    `engine`'s pool.  Returns the number of pages imported; 0 means
    re-prefill (origin had nothing worth shipping, the pool is too hot
    to host the import, or the transfer failed — the pool is NEVER
    left referencing partial data)."""
    t0 = time.monotonic()
    with _tracing.span("serve", "serve.kv_migrate",
                       args={"engine": engine.name,
                             "origin": f"{rdv.get('host')}:"
                                       f"{rdv.get('port')}"}) as h:
        imported = 0
        outcome = "failed"
        try:
            imported = await _pull_impl(rdv, tokens, engine, timeout)
            outcome = "imported" if imported else "skipped"
        except asyncio.CancelledError:
            raise
        except Exception as e:
            logger.warning("kv migration from %s:%s failed (%r); "
                           "falling back to re-prefill",
                           rdv.get("host"), rdv.get("port"), e)
        h.args["pages"] = imported
        h.args["outcome"] = outcome
        MIGRATION_SECONDS.observe(
            time.monotonic() - t0,
            tags={"engine": engine.name, "outcome": outcome})
        if imported:
            PAGES_MIGRATED_COUNTER.inc(
                imported, tags={"engine": engine.name})
        return imported


async def _pull_impl(rdv: Dict, tokens: Sequence[int], engine,
                     timeout: float) -> int:
    tokens = [int(t) for t in tokens]
    if len(tokens) // engine.page_size < _cfg.serve_kv_min_migrate_pages:
        return 0  # can't clear the crossover even on a full match
    conn = await protocol.Connection.connect(
        rdv["host"], rdv["port"], name="kv-migrate",
        timeout=min(timeout, _cfg.connect_timeout_s))
    xid = None
    try:
        meta = await conn.request(
            "kv_export_begin",
            {"engine": rdv.get("engine", "default"), "tokens": tokens},
            timeout=timeout)
        if not isinstance(meta, dict) or meta.get("error") \
                or not meta.get("n"):
            return 0
        xid = meta["xid"]
        n, nb = meta["n"], meta["page_nbytes"]
        buf = bytearray(n * nb)
        mv = memoryview(buf)
        if not _read_samehost(meta, mv):
            await _pull_wire(conn, meta, mv, timeout)
        crcs = meta["crc"]
        for i in range(n):
            if zlib.crc32(mv[i * nb:(i + 1) * nb]) != crcs[i]:
                raise RuntimeError(f"kv page {i} CRC mismatch")
        dt = np.dtype(meta["dtype"])
        knb = meta["k_nbytes"]
        kshape, vshape = tuple(meta["shape_k"]), tuple(meta["shape_v"])
        k = np.empty((n,) + kshape, dt)
        v = np.empty((n,) + vshape, dt)
        for i in range(n):
            base = i * nb
            k[i] = np.frombuffer(
                mv[base:base + knb], dt).reshape(kshape)
            v[i] = np.frombuffer(
                mv[base + knb:base + nb], dt).reshape(vshape)
        matched = tokens[:meta["matched_tokens"]]
        return await _on_worker(
            engine, lambda: engine.kv_import(matched, k, v),
            timeout=timeout)
    finally:
        if xid is not None:
            # Seal (or abort): ONLY now may the origin drop its pins.
            try:
                await conn.request("kv_export_end", {"xid": xid},
                                   timeout=5)
            except Exception:
                pass  # origin's TTL sweep reclaims the export
        try:
            await conn.close()
        except Exception:
            pass


def _read_samehost(meta: Dict, mv: memoryview) -> bool:
    """Same-host fast path: the origin's staging file read directly
    (one memcpy off /dev/shm).  Any miss — no path, file gone, size
    mismatch — falls back to the wire."""
    path = meta.get("path")
    if not path or not _cfg.serve_kv_samehost:
        return False
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return False
    if len(data) != len(mv):
        return False
    mv[:] = data
    return True


async def _pull_wire(conn, meta: Dict, mv: memoryview,
                     timeout: float) -> None:
    n, nb = meta["n"], meta["page_nbytes"]

    def maker(i):
        async def go():
            rep = await conn.request_blob(
                "kv_fetch_page",
                {"xid": meta["xid"], "i": i, "gen": meta["gen"]},
                mv[i * nb:(i + 1) * nb], timeout=timeout)
            if isinstance(rep, dict) and rep.get("error"):
                raise RuntimeError(str(rep["error"]))
            got = rep.get("len") if isinstance(rep, dict) else None
            if got != nb:
                # A short delivery fills only a prefix of the slice;
                # counting it done would seal garbage in the tail.
                raise RuntimeError(f"short kv page: {got} of {nb}")
        return go

    await run_windowed([maker(i) for i in range(n)],
                       max(1, _cfg.serve_kv_migration_window_chunks))


# ------------------------------------------------------------- local path

def migrate_local(src_engine, dst_engine, tokens: Sequence[int],
                  timeout: float = 30.0) -> int:
    """Same-process migration (two engines, one host): the export's
    host staging array hands straight to the import — the same
    pin/commit/seal sequence as the wire path minus the frames.  Used
    by in-process tests and the bench's crossover leg; returns pages
    imported (0 = re-prefill)."""
    tokens = [int(t) for t in tokens]
    exp = src_engine.run_on_worker(
        lambda: src_engine.kv_export(tokens), timeout=timeout)
    if exp is None:
        return 0
    try:
        if len(exp["k"]) < _cfg.serve_kv_min_migrate_pages:
            return 0
        matched = tokens[:exp["matched_tokens"]]
        n = dst_engine.run_on_worker(
            lambda: dst_engine.kv_import(matched, exp["k"], exp["v"]),
            timeout=timeout)
        if n:
            PAGES_MIGRATED_COUNTER.inc(
                n, tags={"engine": dst_engine.name})
        return n
    finally:
        src_engine.run_on_worker(
            lambda: src_engine.kv_export_release(exp["pages"]),
            timeout=timeout)
