"""GenerationEngine: iteration-level scheduling over a slot-pool KV cache.

The decode loop of models/decode.py serves one batch from arrival to
completion; here the batch dimension becomes a POOL OF SLOTS that
requests flow through independently (Orca's continuous batching, vLLM's
slot recycling without paging — whole static-shape cache rows are the
recycling unit, which is the TPU-native choice):

  * a fixed [L, num_slots, max_seq, Hkv, Dh] cache is allocated once;
  * arriving requests wait in an FCFS queue (scheduler.py) and are
    prefilled ONE CHUNK PER TICK into a batch-1 scratch cache
    (chunk_step), so admission never stalls decoding for more than one
    chunk of prefill compute;
  * a finished prefill is spliced into its reserved slot
    (decode.insert_cache_slot) and the row joins the fused decode batch;
  * every tick runs ONE decode_step across all slots with a per-row
    position vector — rows at different depths share the dispatch;
  * each sampled token is pushed to that request's TokenStream
    immediately (streaming TTFT = prefill time, not batch time);
  * rows hitting EOS / max_new_tokens are evicted, their slot zeroed
    (decode.reset_cache_slot) and reused by the next admission.

The device loop runs on a dedicated worker thread: jax dispatch blocks,
and the replica's asyncio loop must stay free to serve stream polls.
Greedy sampling stays on device (argmax); temperature>0 rows sample
host-side from the row's logits with a per-request seeded RNG.

Parity contract (tested): with temperature=0 the tokens a request
streams are bit-identical to decode.generate() run on that prompt
alone — chunked prefill, slot insertion, and per-row decode are pure
scheduling transforms, never result transforms.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import logging
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import decode
from ray_tpu.serve.llm.scheduler import EngineOverloadedError, FCFSScheduler
from ray_tpu.util import metrics as _metrics

logger = logging.getLogger(__name__)

# Latency boundaries tuned for token-scale events (the default metric
# buckets start at 5ms and top out at 10s — fine for TTFT, too coarse
# for inter-token gaps on a fast chip).
_LATENCY_BOUNDARIES = [
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5,
    5, 10, 30]

TTFT_HISTOGRAM = _metrics.Histogram(
    "serve_llm_ttft_seconds",
    "Time from submit() to the first streamed token",
    boundaries=_LATENCY_BOUNDARIES, tag_keys=("engine",))
ITL_HISTOGRAM = _metrics.Histogram(
    "serve_llm_inter_token_seconds",
    "Gap between consecutive streamed tokens of one request",
    boundaries=_LATENCY_BOUNDARIES, tag_keys=("engine",))
TOKENS_COUNTER = _metrics.Counter(
    "serve_llm_tokens_generated_total",
    "Tokens streamed to clients", tag_keys=("engine",))
REQUESTS_COUNTER = _metrics.Counter(
    "serve_llm_requests_total",
    "Requests by terminal status",
    tag_keys=("engine", "status"))
QUEUE_GAUGE = _metrics.Gauge(
    "serve_llm_queue_depth",
    "Requests waiting for a slot (admission queue)",
    tag_keys=("engine",))
OCCUPANCY_GAUGE = _metrics.Gauge(
    "serve_llm_slot_occupancy",
    "Fraction of KV-cache slots mid-generation", tag_keys=("engine",))
THROUGHPUT_GAUGE = _metrics.Gauge(
    "serve_llm_tokens_per_sec",
    "Streamed tokens/sec over the last measurement window",
    tag_keys=("engine",))

class TokenStream:
    """Per-request stream of generated token ids.

    Producer is the engine's worker thread; consumers may be sync
    (`for tok in stream`, `stream.result()`) or async
    (`async for tok in stream`, `await stream.collect()`) on any event
    loop — waiters are woken through loop.call_soon_threadsafe, so no
    consumer loop ever blocks on the device."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._buf: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._wakeups: List = []   # zero-arg callables, fired once each
        self._done = False
        self._error: Optional[BaseException] = None
        self._cancel = threading.Event()
        self._partial: List[int] = []  # result()'s drained-so-far stash

    # -- producer side (engine worker thread) --

    def _push(self, token: int):
        with self._lock:
            self._buf.append(token)
            wakeups, self._wakeups = self._wakeups, []
        self._fire(wakeups)

    def _finish(self, error: Optional[BaseException] = None):
        with self._lock:
            self._done = True
            self._error = error
            wakeups, self._wakeups = self._wakeups, []
        self._fire(wakeups)

    @staticmethod
    def _fire(wakeups):
        for w in wakeups:
            try:
                w()
            except RuntimeError:
                # A consumer abandoned its wait and closed its event
                # loop; its wakeup is moot and must not poison the
                # engine's worker thread.
                pass

    # -- consumer side --

    def cancel(self):
        """Ask the engine to stop this request; the stream finishes
        with whatever tokens were already generated."""
        self._cancel.set()

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def _pop_or_register(self, wakeup):
        """Pop a buffered item, or register a wakeup and return _DONE /
        None.  Returns (kind, value): ('tok', t) | ('end', err) |
        ('wait', None)."""
        with self._lock:
            if self._buf:
                return "tok", self._buf.popleft()
            if self._done:
                return "end", self._error
            self._wakeups.append(wakeup)
            return "wait", None

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio
        while True:
            loop = asyncio.get_running_loop()
            ev = asyncio.Event()
            kind, val = self._pop_or_register(
                lambda: loop.call_soon_threadsafe(ev.set))
            if kind == "tok":
                return val
            if kind == "end":
                if val is not None:
                    raise val
                raise StopAsyncIteration
            await ev.wait()

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            ev = threading.Event()
            kind, val = self._pop_or_register(ev.set)
            if kind == "tok":
                return val
            if kind == "end":
                if val is not None:
                    raise val
                raise StopIteration
            ev.wait()

    async def collect(self) -> List[int]:
        """Await the full generation as a token list."""
        return [t async for t in self]

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block (sync) for the full generation.  On TimeoutError no
        tokens are lost: whatever was drained is kept and a later
        result() call returns the COMPLETE list from the start."""
        deadline = None if timeout is None else time.monotonic() + timeout
        out = self._partial  # resume whatever an earlier timeout drained
        while True:
            ev = threading.Event()
            kind, val = self._pop_or_register(ev.set)
            if kind == "tok":
                out.append(val)
            elif kind == "end":
                if val is not None:
                    raise val
                self._partial = []
                return list(out)
            else:
                remain = None if deadline is None \
                    else deadline - time.monotonic()
                if remain is not None and remain <= 0:
                    raise TimeoutError(
                        f"request {self.request_id} still generating "
                        f"after {timeout}s")
                ev.wait(remain)


@dataclasses.dataclass
class EngineStats:
    queue_depth: int
    active_slots: int
    num_slots: int
    tokens_generated: int
    requests_completed: int
    requests_rejected: int
    requests_cancelled: int
    tokens_per_sec: float
    uptime_s: float

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class _Request:
    __slots__ = ("id", "prompt", "max_new_tokens", "temperature",
                 "top_k", "eos_token", "rng", "stream", "submit_t",
                 "first_token_t", "last_token_t", "emitted")

    def __init__(self, rid, prompt, max_new_tokens, temperature, top_k,
                 eos_token, seed):
        self.id = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.eos_token = eos_token
        self.rng = np.random.default_rng(seed) if temperature > 0 else None
        self.stream = TokenStream(rid)
        self.submit_t = time.monotonic()
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        self.emitted = 0


class _PrefillState:
    __slots__ = ("req", "slot", "next_start")

    def __init__(self, req: _Request, slot: int):
        self.req = req
        self.slot = slot
        self.next_start = 0


@functools.partial(jax.jit, static_argnames=("cfg", "with_logits"),
                   donate_argnames=("cache",))
def _fused_tick(params, token, pos, cache, cfg, with_logits):
    """One decode_step across every slot (per-row positions) + on-device
    greedy argmax; logits ride back to host only when a sampled-mode
    request is active."""
    logits, cache = decode.decode_step(params, token, pos, cache, cfg)
    sampled = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return sampled, (logits if with_logits else None), cache


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache",))
def _prefill_chunk(params, tokens, pos, cache, cfg):
    return decode.chunk_step(params, tokens, pos, cache, cfg)


def _host_sample(row_logits: np.ndarray, temperature: float, top_k: int,
                 rng: np.random.Generator) -> int:
    """Temperature/top-k sampling on host from one row's fp32 logits."""
    logits = row_logits.astype(np.float64) / max(temperature, 1e-6)
    top_k = min(top_k, len(logits))  # a huge k means "no restriction"
    if top_k > 0:
        kth = np.sort(logits)[-top_k]
        logits = np.where(logits < kth, -np.inf, logits)
    logits -= logits.max()
    probs = np.exp(logits)
    probs /= probs.sum()
    return int(rng.choice(len(probs), p=probs))


class GenerationEngine:
    """Continuous-batching generation over a fixed pool of cache slots.

    Knobs:
      num_slots        decode batch width B (slots recycled on finish)
      max_seq          cache width S; prompt + max_new_tokens <= S
      prefill_chunk    tokens of prompt prefilled per engine tick
      max_queue_len    admission-queue cap; past it submit() raises
                       EngineOverloadedError (backpressure)
      name             metrics tag value

    `submit()` may be called from any thread / event loop; the returned
    TokenStream is consumable sync or async.  `start()` is implicit on
    first submit; `stop()` fails outstanding work and joins the worker.
    """

    def __init__(self, params, cfg, *, num_slots: int = 4,
                 max_seq: Optional[int] = None, prefill_chunk: int = 32,
                 max_queue_len: int = 64,
                 default_max_new_tokens: int = 64,
                 name: str = "default"):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if getattr(cfg, "n_experts", 0):
            raise NotImplementedError(
                "continuous batching supports dense models only "
                "(decode has no MoE routing cache)")
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_seq = int(max_seq or cfg.max_seq)
        self.prefill_chunk = min(prefill_chunk, self.max_seq)
        self.default_max_new_tokens = default_max_new_tokens
        self.name = name

        self._scheduler = FCFSScheduler(max_queue_len)
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._started_t = time.monotonic()

        # Device state (worker-thread-owned after start).
        self._cache = decode.init_cache(cfg, num_slots,
                                        max_seq=self.max_seq)
        self._scratch = decode.init_cache(cfg, 1, max_seq=self.max_seq)
        self._pos = np.zeros((num_slots,), np.int32)
        self._tok = np.zeros((num_slots,), np.int32)
        self._slots: List[Optional[_Request]] = [None] * num_slots
        self._prefill: Optional[_PrefillState] = None

        # Counters (worker thread writes; stats() reads).
        self._tokens_generated = 0
        self._completed = 0
        self._rejected = 0
        self._cancelled = 0
        self._win_t = time.monotonic()
        self._win_tokens = 0

        self._tags = {"engine": name}
        QUEUE_GAUGE.set(0, tags=self._tags)
        OCCUPANCY_GAUGE.set(0.0, tags=self._tags)

    # ------------------------------------------------------------------
    # Public API

    def start(self):
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = False
            self._thread = threading.Thread(
                target=self._run, name=f"llm-engine-{self.name}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 30.0):
        """Stop the worker; outstanding requests fail with
        RuntimeError("engine stopped")."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
        err = RuntimeError("engine stopped")
        with self._cond:
            leftovers = self._scheduler.drain()
            if self._prefill is not None:
                leftovers.append(self._prefill.req)
                self._prefill = None
            QUEUE_GAUGE.set(0, tags=self._tags)
        for req in leftovers:
            req.stream._finish(err)
        for s, req in enumerate(self._slots):
            if req is not None:
                req.stream._finish(err)
                self._slots[s] = None
        OCCUPANCY_GAUGE.set(0.0, tags=self._tags)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_k: int = 0,
               eos_token: Optional[int] = None, seed: int = 0,
               request_id: Optional[str] = None) -> TokenStream:
        """Queue one prompt; returns its TokenStream immediately.

        Raises EngineOverloadedError when the admission queue is full
        and ValueError for prompts the cache can never hold."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        max_new = int(self.default_max_new_tokens
                      if max_new_tokens is None else max_new_tokens)
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
        if len(prompt) + max_new > self.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceeds the engine's max_seq={self.max_seq}")
        # Sampling knobs are validated HERE, the single entry point: a
        # bad value surfacing later, inside the worker tick, would fail
        # every co-resident request (_fail_all), not just this one.
        temperature = float(temperature)
        top_k = int(top_k)
        if not np.isfinite(temperature) or temperature < 0:
            raise ValueError(f"temperature must be finite and >= 0, "
                             f"got {temperature}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        req = _Request(request_id or uuid.uuid4().hex[:12], prompt,
                       max_new, temperature, top_k, eos_token, seed)
        with self._cond:
            try:
                self._scheduler.enqueue(req)
            except EngineOverloadedError:
                self._rejected += 1
                REQUESTS_COUNTER.inc(tags={**self._tags,
                                           "status": "rejected"})
                raise
            QUEUE_GAUGE.set(self._scheduler.depth, tags=self._tags)
            self._cond.notify_all()
        self.start()
        return req.stream

    async def generate(self, prompt: Sequence[int], **kw) -> List[int]:
        """submit() + collect(): the whole generation as a list."""
        return await self.submit(prompt, **kw).collect()

    def stats(self) -> EngineStats:
        now = time.monotonic()
        win = now - self._win_t
        tps = self._win_tokens / win if win > 0.2 else 0.0
        return EngineStats(
            queue_depth=self._scheduler.depth
            + (1 if self._prefill is not None else 0),
            active_slots=sum(r is not None for r in self._slots),
            num_slots=self.num_slots,
            tokens_generated=self._tokens_generated,
            requests_completed=self._completed,
            requests_rejected=self._rejected,
            requests_cancelled=self._cancelled,
            tokens_per_sec=round(tps, 2),
            uptime_s=round(now - self._started_t, 3))

    # ------------------------------------------------------------------
    # Worker thread

    def _run(self):
        while True:
            with self._cond:
                while not self._stop and not self._has_work_locked():
                    self._cond.wait(timeout=0.1)
                if self._stop:
                    return
            try:
                self._admit_one_chunk()
                self._decode_tick()
            except Exception as e:  # engine-level fault: fail fast,
                logger.exception("engine %s tick failed", self.name)
                self._fail_all(e)

    def _has_work_locked(self) -> bool:
        return (self._scheduler.depth > 0 or self._prefill is not None
                or any(r is not None for r in self._slots))

    def _free_slot(self) -> Optional[int]:
        reserved = self._prefill.slot if self._prefill else -1
        for s, r in enumerate(self._slots):
            if r is None and s != reserved:
                return s
        return None

    def _admit_one_chunk(self):
        """Advance admission by AT MOST one prefill chunk (the bound on
        how long a tick's decode can be delayed by an arrival)."""
        if self._prefill is None:
            slot = self._free_slot()
            if slot is None:
                return
            with self._cond:
                req = self._scheduler.next_request()
                QUEUE_GAUGE.set(self._scheduler.depth, tags=self._tags)
            while req is not None and req.stream.cancelled:
                self._finish_request(req, "cancelled")
                with self._cond:
                    req = self._scheduler.next_request()
                    QUEUE_GAUGE.set(self._scheduler.depth,
                                    tags=self._tags)
            if req is None:
                return
            # The slot is reserved now so the insert at the end of
            # prefill can never find the pool full.
            self._scratch = decode.reset_cache_slot(
                self._scratch, jnp.int32(0))
            self._prefill = _PrefillState(req, slot)

        st = self._prefill
        req = st.req
        if req.stream.cancelled:
            self._prefill = None
            self._finish_request(req, "cancelled")
            return
        L = len(req.prompt)
        start = st.next_start
        width = min(self.prefill_chunk, self.max_seq - start)
        real = req.prompt[start:start + width]
        chunk = np.zeros((1, width), np.int32)
        chunk[0, :len(real)] = real
        logits, self._scratch = _prefill_chunk(
            self.params, jnp.asarray(chunk), jnp.int32(start),
            self._scratch, self.cfg)
        st.next_start = start + width
        if st.next_start < L:
            return  # more chunks to go; decode proceeds meanwhile

        # Prefill complete: sample the first token from the last REAL
        # column of the final chunk (pad columns carry garbage).
        self._prefill = None
        row = np.asarray(logits[0, len(real) - 1])
        first = self._sample_host(row, req)
        now = time.monotonic()
        if req.eos_token is not None and first == req.eos_token:
            self._finish_request(req, "completed")
            return
        if req.max_new_tokens == 1:
            # Nothing left to decode: never joins the batch.
            self._emit(req, first, now)
            self._finish_request(req, "completed")
            return
        # Join the decode batch BEFORE the token is emitted: a consumer
        # woken by its first token must observe the request as an
        # active slot, not a phantom.
        self._cache = decode.insert_cache_slot(
            self._cache, self._scratch, jnp.int32(st.slot))
        self._pos[st.slot] = L
        self._tok[st.slot] = first
        self._slots[st.slot] = req
        self._update_occupancy()
        self._emit(req, first, now)

    def _decode_tick(self):
        actives = [s for s in range(self.num_slots)
                   if self._slots[s] is not None]
        if not actives:
            return
        sample_rows = [s for s in actives
                       if self._slots[s].temperature > 0]
        sampled, logits, self._cache = _fused_tick(
            self.params, jnp.asarray(self._tok), jnp.asarray(self._pos),
            self._cache, self.cfg, with_logits=bool(sample_rows))
        sampled = np.asarray(sampled)
        if sample_rows:
            # Host transfer scales with the SAMPLING rows, not the
            # whole pool: one temperature>0 request must not ship
            # [num_slots, vocab] off-device every tick.
            logits_np = np.asarray(
                logits[jnp.asarray(np.asarray(sample_rows, np.int32))])
            row_of = {s: i for i, s in enumerate(sample_rows)}
        now = time.monotonic()
        for s in actives:
            req = self._slots[s]
            if req.stream.cancelled:
                self._evict(s, "cancelled")
                continue
            if req.temperature > 0:
                t = _host_sample(logits_np[row_of[s]], req.temperature,
                                 req.top_k, req.rng)
            else:
                t = int(sampled[s])
            self._tok[s] = t
            self._pos[s] += 1
            if req.eos_token is not None and t == req.eos_token:
                self._evict(s, "completed")
                continue
            self._emit(req, t, now)
            if req.emitted >= req.max_new_tokens:
                self._evict(s, "completed")

    def _sample_host(self, row_logits: np.ndarray, req: _Request) -> int:
        if req.temperature > 0:
            return _host_sample(row_logits, req.temperature, req.top_k,
                                req.rng)
        return int(row_logits.argmax())

    def _emit(self, req: _Request, token: int, now: float):
        req.emitted += 1
        if req.first_token_t is None:
            req.first_token_t = now
            TTFT_HISTOGRAM.observe(now - req.submit_t, tags=self._tags)
        else:
            ITL_HISTOGRAM.observe(now - req.last_token_t,
                                  tags=self._tags)
        req.last_token_t = now
        self._tokens_generated += 1
        self._win_tokens += 1
        TOKENS_COUNTER.inc(tags=self._tags)
        if now - self._win_t >= 0.5:
            THROUGHPUT_GAUGE.set(
                self._win_tokens / (now - self._win_t),
                tags=self._tags)
            self._win_t = now
            self._win_tokens = 0
        req.stream._push(token)

    def _evict(self, slot: int, status: str):
        req = self._slots[slot]
        self._slots[slot] = None
        self._pos[slot] = 0
        self._tok[slot] = 0
        self._cache = decode.reset_cache_slot(
            self._cache, jnp.int32(slot))
        self._update_occupancy()
        self._finish_request(req, status)

    def _finish_request(self, req: _Request, status: str):
        if status == "cancelled":
            self._cancelled += 1
        else:
            self._completed += 1
        REQUESTS_COUNTER.inc(tags={**self._tags, "status": status})
        req.stream._finish()

    def _update_occupancy(self):
        OCCUPANCY_GAUGE.set(
            sum(r is not None for r in self._slots) / self.num_slots,
            tags=self._tags)

    def _fail_all(self, err: BaseException):
        if self._prefill is not None:
            self._prefill.req.stream._finish(err)
            self._prefill = None
        with self._cond:
            leftovers = self._scheduler.drain()
            QUEUE_GAUGE.set(0, tags=self._tags)
        for req in leftovers:
            req.stream._finish(err)
        for s in range(self.num_slots):
            req = self._slots[s]
            if req is not None:
                self._slots[s] = None
                req.stream._finish(err)
        self._pos[:] = 0
        self._tok[:] = 0
        # Rebuild device state: the donated cache may be mid-flight.
        self._cache = decode.init_cache(
            self.cfg, self.num_slots, max_seq=self.max_seq)
        self._scratch = decode.init_cache(
            self.cfg, 1, max_seq=self.max_seq)
        self._update_occupancy()
