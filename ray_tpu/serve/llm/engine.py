"""GenerationEngine: iteration-level scheduling over a PAGED KV cache.

The decode loop of models/decode.py serves one batch from arrival to
completion; here the batch dimension becomes a pool of rows that
requests flow through independently (Orca's continuous batching), and
the KV memory behind those rows is a pool of fixed-size PAGES addressed
through per-row block tables (vLLM's PagedAttention) with a radix
prefix cache sharing pages between requests (SGLang's RadixAttention at
page granularity):

  * one [L, num_pages, page_size, Hkv, Dh] page pool is allocated once;
    page 0 is a TRASH page — inactive batch rows' block tables point at
    it, so the fused tick's static-shape scatter always has somewhere
    harmless to write;
  * a request reserves ceil((prompt + max_new + spec slack)/page) pages
    at admission (all-or-nothing, so a resident request can never be
    starved mid-generation) — admission is FREE-PAGE-bounded, not
    row-bounded: mixed-length workloads pack by what they actually
    need instead of every request pinning max_seq;
  * the radix prefix cache maps full-page token prefixes to pages with
    live K/V: a prompt that hits skips prefill for the shared pages
    (refcounted — evicting one sharer never frees a page another still
    gathers) and goes straight to chunked prefill of the tail;
  * arriving requests wait in an FCFS queue (scheduler.py) and are
    prefilled ONE CHUNK PER TICK directly into their own pages through
    their block table (no scratch cache, no slot splice), so admission
    never stalls decoding for more than one chunk of prefill compute;
  * every tick runs ONE fused paged_decode_step across all rows with a
    per-row position vector; when speculation is on and any greedy row
    has a prompt-lookup draft, the tick is instead ONE fused
    paged_chunk_step verifying (pending token + k drafts) per row —
    per-row acceptance (not the lockstep batch-minimum of standalone
    generate()), so one row's miss never throttles another's streak;
  * each sampled token is pushed to that request's TokenStream
    immediately; rows hitting EOS/max-tokens are evicted by FREEING
    their pages (host-side accounting only — stale K/V in a recycled
    page is overwritten before any unmasked read, so there is no
    zeroing pass on the device).

The device loop runs on a dedicated worker thread: jax dispatch blocks,
and the replica's asyncio loop must stay free to serve stream polls.
Greedy sampling stays on device (argmax); temperature>0 rows sample
host-side from the row's logits with a per-request seeded RNG.

Parity contract (tested): with temperature=0 the tokens a request
streams are bit-identical to decode.generate() run on that prompt
alone — chunked prefill, paging, prefix-cache hits, and speculative
verification are pure scheduling transforms, never result transforms.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import logging
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu._private import locksan
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import GLOBAL_CONFIG as _cfg
from ray_tpu.models import decode
from ray_tpu.serve.llm.kv_tier import (HostKVArena, KVPageStore,
                                       frame_crc, page_frame,
                                       split_frame)
from ray_tpu.serve.llm.paging import (TIER_HOST, TIER_POOL, TIER_STORE,
                                      BlockAllocator, RadixPrefixCache,
                                      prefix_fingerprints)
from ray_tpu.serve.llm.scheduler import EngineOverloadedError, FCFSScheduler
from ray_tpu.util import metrics as _metrics

logger = logging.getLogger(__name__)

# Latency boundaries tuned for token-scale events (the default metric
# buckets start at 5ms and top out at 10s — fine for TTFT, too coarse
# for inter-token gaps on a fast chip).
_LATENCY_BOUNDARIES = [
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5,
    5, 10, 30]

TTFT_HISTOGRAM = _metrics.Histogram(
    "serve_llm_ttft_seconds",
    "Time from submit() to the first streamed token",
    boundaries=_LATENCY_BOUNDARIES, tag_keys=("engine",))
ITL_HISTOGRAM = _metrics.Histogram(
    "serve_llm_inter_token_seconds",
    "Gap between consecutive streamed tokens of one request",
    boundaries=_LATENCY_BOUNDARIES, tag_keys=("engine",))
TOKENS_COUNTER = _metrics.Counter(
    "serve_llm_tokens_generated_total",
    "Tokens streamed to clients", tag_keys=("engine",))
REQUESTS_COUNTER = _metrics.Counter(
    "serve_llm_requests_total",
    "Requests by terminal status",
    tag_keys=("engine", "status"))
QUEUE_GAUGE = _metrics.Gauge(
    "serve_llm_queue_depth",
    "Requests waiting for admission (excludes the one mid-prefill; "
    "EngineStats.queue_depth adds it)",
    tag_keys=("engine",))
OCCUPANCY_GAUGE = _metrics.Gauge(
    "serve_llm_slot_occupancy",
    "Fraction of decode batch rows mid-generation", tag_keys=("engine",))
THROUGHPUT_GAUGE = _metrics.Gauge(
    "serve_llm_tokens_per_sec",
    "Streamed tokens/sec over the last measurement window",
    tag_keys=("engine",))
KV_BLOCKS_TOTAL_GAUGE = _metrics.Gauge(
    "serve_llm_kv_blocks_total",
    "Allocatable KV pages in the pool (excludes the trash page)",
    tag_keys=("engine",))
KV_BLOCKS_FREE_GAUGE = _metrics.Gauge(
    "serve_llm_kv_blocks_free",
    "KV pages currently on the free list", tag_keys=("engine",))
PREFIX_HITS_COUNTER = _metrics.Counter(
    "serve_llm_prefix_cache_hits_total",
    "Admissions whose prompt hit >=1 cached prefix page",
    tag_keys=("engine",))
PREFIX_MISSES_COUNTER = _metrics.Counter(
    "serve_llm_prefix_cache_misses_total",
    "Admissions with no cached prefix page", tag_keys=("engine",))
SPEC_ACCEPTED_COUNTER = _metrics.Counter(
    "serve_llm_spec_accepted_tokens_total",
    "Draft tokens accepted by speculative verification",
    tag_keys=("engine",))
KV_TIER_PAGES_GAUGE = _metrics.Gauge(
    "serve_llm_kv_tier_pages",
    "Prefix-cache pages by tier (t0=decode pool, t1=host arena, "
    "t2=store)", tag_keys=("engine", "tier"))
KV_DEMOTIONS_COUNTER = _metrics.Counter(
    "serve_llm_kv_demotions_total",
    "Pages demoted out of the decode pool / host arena, by "
    "destination tier", tag_keys=("engine", "to"))
KV_PROMOTIONS_COUNTER = _metrics.Counter(
    "serve_llm_kv_promotions_total",
    "Demoted pages promoted back into the decode pool on a prefix "
    "hit", tag_keys=("engine",))
RESURRECTIONS_COUNTER = _metrics.Counter(
    "serve_llm_session_resurrections_total",
    "Durable sessions restored from the store tier",
    tag_keys=("engine",))


class TokenStream:
    """Per-request stream of generated token ids.

    Producer is the engine's worker thread; consumers may be sync
    (`for tok in stream`, `stream.result()`) or async
    (`async for tok in stream`, `await stream.collect()`) on any event
    loop — waiters are woken through loop.call_soon_threadsafe, so no
    consumer loop ever blocks on the device."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._buf: collections.deque = collections.deque()
        self._lock = locksan.make_lock("TokenStream._lock")
        self._wakeups: List = []   # zero-arg callables, fired once each
        self._done = False
        self._error: Optional[BaseException] = None
        self._cancel = threading.Event()
        self._partial: List[int] = []  # result()'s drained-so-far stash

    # -- producer side (engine worker thread) --

    def _push(self, token: int):
        with self._lock:
            self._buf.append(token)
            wakeups, self._wakeups = self._wakeups, []
        self._fire(wakeups)

    def _finish(self, error: Optional[BaseException] = None):
        with self._lock:
            self._done = True
            self._error = error
            wakeups, self._wakeups = self._wakeups, []
        self._fire(wakeups)

    @staticmethod
    def _fire(wakeups):
        for w in wakeups:
            try:
                w()
            except RuntimeError:
                # A consumer abandoned its wait and closed its event
                # loop; its wakeup is moot and must not poison the
                # engine's worker thread.
                pass

    # -- consumer side --

    def cancel(self):
        """Ask the engine to stop this request; the stream finishes
        with whatever tokens were already generated."""
        self._cancel.set()

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def _pop_or_register(self, wakeup):
        """Pop a buffered item, or register a wakeup and return _DONE /
        None.  Returns (kind, value): ('tok', t) | ('end', err) |
        ('wait', None)."""
        with self._lock:
            if self._buf:
                return "tok", self._buf.popleft()
            if self._done:
                return "end", self._error
            self._wakeups.append(wakeup)
            return "wait", None

    def __aiter__(self):
        return self

    async def __anext__(self):
        import asyncio
        while True:
            loop = asyncio.get_running_loop()
            ev = asyncio.Event()
            kind, val = self._pop_or_register(
                lambda: loop.call_soon_threadsafe(ev.set))
            if kind == "tok":
                return val
            if kind == "end":
                if val is not None:
                    raise val
                raise StopAsyncIteration
            await ev.wait()

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            ev = threading.Event()
            kind, val = self._pop_or_register(ev.set)
            if kind == "tok":
                return val
            if kind == "end":
                if val is not None:
                    raise val
                raise StopIteration
            ev.wait()

    async def collect(self) -> List[int]:
        """Await the full generation as a token list."""
        return [t async for t in self]

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block (sync) for the full generation.  On TimeoutError no
        tokens are lost: whatever was drained is kept and a later
        result() call returns the COMPLETE list from the start."""
        deadline = None if timeout is None else time.monotonic() + timeout
        out = self._partial  # resume whatever an earlier timeout drained
        while True:
            ev = threading.Event()
            kind, val = self._pop_or_register(ev.set)
            if kind == "tok":
                out.append(val)
            elif kind == "end":
                if val is not None:
                    raise val
                self._partial = []
                return list(out)
            else:
                remain = None if deadline is None \
                    else deadline - time.monotonic()
                if remain is not None and remain <= 0:
                    raise TimeoutError(
                        f"request {self.request_id} still generating "
                        f"after {timeout}s")
                ev.wait(remain)


@dataclasses.dataclass
class EngineStats:
    queue_depth: int
    active_slots: int
    num_slots: int
    tokens_generated: int
    requests_completed: int
    requests_rejected: int
    requests_cancelled: int
    tokens_per_sec: float
    uptime_s: float
    page_size: int = 0
    kv_blocks_total: int = 0
    kv_blocks_free: int = 0
    prefix_cache_hits: int = 0
    prefix_cache_misses: int = 0
    prefix_hit_tokens: int = 0
    spec_drafted_tokens: int = 0
    spec_accepted_tokens: int = 0
    kv_t1_pages: int = 0
    kv_t2_pages: int = 0
    kv_demotions: int = 0
    kv_promotions: int = 0
    session_resurrections: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _span_for(req: "_Request", name: str, t0_mono: float,
              dur_s: float, args: Optional[Dict] = None) -> None:
    """One engine-stage span linked into the REQUEST's trace (captured
    at submit() — the engine worker thread has no contextvar context of
    its own).  t0 is monotonic (the engine's clock); re-anchored to the
    epoch so the span aligns with every other process's events."""
    if not _tracing.enabled():
        return
    tr = req.trace
    link = None
    if tr is not None:
        link = {"trace_id": tr["trace_id"],
                "span_id": _tracing.fresh_id(),
                "parent_id": tr.get("parent_id")}
    _tracing.record("engine", name,
                    time.time() - (time.monotonic() - t0_mono),
                    dur_s, trace=link, args=args)


class _Request:
    __slots__ = ("id", "prompt", "max_new_tokens", "temperature",
                 "top_k", "eos_token", "rng", "stream", "submit_t",
                 "first_token_t", "last_token_t", "emitted", "n_blocks",
                 "pages", "tokens", "prefix_hit_tokens", "ngram_map",
                 "ngram_upto", "trace", "session")

    def __init__(self, rid, prompt, max_new_tokens, temperature, top_k,
                 eos_token, seed, n_blocks, session=None,
                 rng_state=None):
        self.id = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.eos_token = eos_token
        self.session = session   # durable-session id (None = ephemeral)
        self.rng = np.random.default_rng(seed) if temperature > 0 else None
        if self.rng is not None and rng_state is not None:
            # Resurrected sampled session: continue the EXACT random
            # stream the checkpoint froze, so the continuation draws
            # what the original replica would have drawn.
            try:
                self.rng.bit_generator.state = rng_state
            except (TypeError, ValueError, KeyError):
                logger.warning("request %s: stale sampler state "
                               "ignored; reseeding", rid)
        self.stream = TokenStream(rid)
        self.submit_t = time.monotonic()
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        self.emitted = 0
        self.n_blocks = n_blocks     # worst-case page reservation
        self.pages: List[int] = []   # held pages (shared prefix + own)
        self.tokens: List[int] = []  # prompt + produced (draft source)
        self.prefix_hit_tokens = 0
        self.ngram_map: Dict = {}    # trailing-ngram -> latest end pos
        self.ngram_upto = 0          # positions indexed so far
        # The submitter's span context: TTFT-stage spans (queue /
        # prefill / first_tick) recorded on the engine worker thread
        # link under the serve request's trace.
        self.trace = _tracing.current_dict()


class _PrefillState:
    __slots__ = ("req", "slot", "next_start", "bt_row", "t0", "chunks")

    def __init__(self, req: _Request, slot: int, start: int, bt_row):
        self.req = req
        self.slot = slot
        self.next_start = start
        self.t0 = time.monotonic()   # prefill-stage span start
        self.chunks = 0
        # The row's block table stays PRIVATE until activation: the
        # fused tick scatters a garbage write for every inactive batch
        # row, and the engine-wide table must keep pointing those rows
        # at the trash page — never at this request's (possibly shared)
        # pages.
        self.bt_row = bt_row


def _lookup_draft(req: "_Request", ngram: int, k: int) -> List[int]:
    """Prompt-lookup draft (host twin of decode's speculative lookup):
    the tokens that followed the most recent EARLIER occurrence of the
    trailing n-gram, which ends at the pending token.  Returns up to k
    tokens ([] when no earlier occurrence exists — a wrong or short
    draft costs a little verify compute, never correctness).

    The request carries an incrementally maintained ngram -> latest-end
    -position map, so a tick's lookup only indexes the tokens appended
    since the last tick (amortized O(1) per generated token) instead of
    rescanning the whole history — the no-match case on non-repetitive
    text is the common one, and it sits on the tick hot path."""
    tokens = req.tokens
    n = len(tokens)
    if n < ngram + 1:
        return []
    # Index windows ENDING at positions [ngram-1, n-2]: the window at
    # n-1 ends at the pending token and must stay out of the map (a
    # draft may only come from a strictly earlier occurrence).
    for p in range(max(req.ngram_upto, ngram - 1), n - 1):
        req.ngram_map[tuple(tokens[p - ngram + 1:p + 1])] = p
    req.ngram_upto = n - 1
    j = req.ngram_map.get(tuple(tokens[n - ngram:]))
    if j is None:
        return []
    return tokens[j + 1:j + 1 + k]


@functools.partial(jax.jit, static_argnames=("cfg", "with_logits"),
                   donate_argnames=("cache",))
def _paged_tick(params, token, pos, cache, block_tables, cfg,
                with_logits):
    """One paged decode_step across every row (per-row positions) +
    on-device greedy argmax; logits ride back to host only when a
    sampled-mode request is active."""
    logits, cache = decode.paged_decode_step(params, token, pos, cache,
                                             block_tables, cfg)
    sampled = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return sampled, (logits if with_logits else None), cache


@functools.partial(jax.jit, static_argnames=("cfg", "with_logits"),
                   donate_argnames=("cache",))
def _paged_verify(params, chunk, pos, cache, block_tables, cfg,
                  with_logits):
    """Fused speculative tick: each row's (pending token + k draft
    tokens) scored in one paged_chunk_step.  preds[b, i] is the greedy
    next token after row b's chunk prefix 0..i; sampling rows read only
    their position-0 logits (their draft columns are dead weight,
    overwritten before any unmasked read)."""
    logits, cache = decode.paged_chunk_step(params, chunk, pos, cache,
                                            block_tables, cfg)
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return preds, (logits[:, 0] if with_logits else None), cache


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache",))
def _prefill_chunk(params, tokens, pos, cache, block_table, cfg):
    return decode.paged_chunk_step(params, tokens, pos, cache,
                                   block_table, cfg)


def _host_sample(row_logits: np.ndarray, temperature: float, top_k: int,
                 rng: np.random.Generator) -> int:
    """Temperature/top-k sampling on host from one row's fp32 logits."""
    logits = row_logits.astype(np.float64) / max(temperature, 1e-6)
    top_k = min(top_k, len(logits))  # a huge k means "no restriction"
    if top_k > 0:
        kth = np.sort(logits)[-top_k]
        logits = np.where(logits < kth, -np.inf, logits)
    logits -= logits.max()
    probs = np.exp(logits)
    probs /= probs.sum()
    return int(rng.choice(len(probs), p=probs))


class GenerationEngine:
    """Continuous-batching generation over a paged KV pool.

    Knobs:
      num_slots        decode batch width B (rows recycled on finish)
      max_seq          per-request bound: prompt + max_new_tokens <= it
      page_size        KV page width in tokens (page_size >= max_seq
                       degenerates to the old one-slot-per-request
                       layout — the bench's "slot mode" baseline)
      kv_pages         allocatable pages in the pool (default:
                       num_slots * ceil(max_seq / page_size) — equal
                       memory to the old contiguous slot pool)
      enable_prefix_cache  share full prompt pages between requests via
                       the radix cache (prefill skipped for shared pages)
      speculate_k / speculate_ngram
                       >0 enables in-engine prompt-lookup speculative
                       decoding for greedy rows (fused verify tick)
      prefill_chunk    tokens of prompt prefilled per engine tick
      max_queue_len    admission-queue cap; past it submit() raises
                       EngineOverloadedError(reason="queue_full")
      kv_commit_factor submit() bounds OUTSTANDING worst-case page
                       demand (waiting + resident) at factor*kv_pages;
                       past it submit() raises
                       EngineOverloadedError(reason="kv_exhausted")
      name             metrics tag value

    `submit()` may be called from any thread / event loop; the returned
    TokenStream is consumable sync or async.  `start()` is implicit on
    first submit; `stop()` fails outstanding work and joins the worker.
    """

    def __init__(self, params, cfg, *, num_slots: int = 4,
                 max_seq: Optional[int] = None, prefill_chunk: int = 32,
                 max_queue_len: int = 64,
                 default_max_new_tokens: int = 64,
                 name: str = "default",
                 page_size: int = 16, kv_pages: Optional[int] = None,
                 enable_prefix_cache: bool = True,
                 speculate_k: int = 0, speculate_ngram: int = 3,
                 kv_commit_factor: float = 4.0,
                 kv_tiering: Optional[bool] = None,
                 kv_store_dir: Optional[str] = None):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        if speculate_k < 0:
            raise ValueError("speculate_k must be >= 0")
        if speculate_k and speculate_ngram < 1:
            raise ValueError("speculate_ngram must be >= 1 when "
                             "speculate_k is set")
        if getattr(cfg, "n_experts", 0):
            raise NotImplementedError(
                "continuous batching supports dense models only "
                "(decode has no MoE routing cache)")
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_seq = int(max_seq or cfg.max_seq)
        self.page_size = int(page_size)
        self.speculate_k = int(speculate_k)
        self.speculate_ngram = int(speculate_ngram)
        # Speculation writes up to k tokens past a row's position before
        # acceptance is known; the reservation slack keeps those writes
        # inside the row's own pages (a write clipped to the trash page
        # would LOSE accepted K/V).  +1 mirrors generate()'s slack.
        self._slack = self.speculate_k + 1 if self.speculate_k else 0
        self._max_blocks = -(-(self.max_seq + self._slack)
                             // self.page_size)
        self._s_virt = self._max_blocks * self.page_size
        # Default sizing includes the speculation slack: every request
        # the max_seq check admits must also fit the pool (a max-length
        # request reserves _max_blocks pages).
        self.kv_pages = int(kv_pages if kv_pages is not None
                            else num_slots * self._max_blocks)
        if self.kv_pages < 1:
            raise ValueError("kv_pages must be >= 1")
        self.prefill_chunk = min(prefill_chunk, self._s_virt)
        self.default_max_new_tokens = default_max_new_tokens
        self.name = name
        # With kv_commit_factor >= 1 a lone request always fits the cap
        # (its n_blocks is bounded by kv_pages via the submit check).
        self._commit_cap = max(1, int(kv_commit_factor * self.kv_pages))

        self._scheduler = FCFSScheduler(max_queue_len)
        self._cond = locksan.make_condition("GenerationEngine._cond")
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._started_t = time.monotonic()
        # Worker-thread command queue (run_on_worker): KV export/import
        # and other paging surgery run BETWEEN ticks on the one thread
        # that owns the device + paging state — the single-owner
        # discipline stays intact and a migration can never stall a
        # tick mid-dispatch.
        self._commands: collections.deque = collections.deque()

        # Device + paging state (worker-thread-owned after start).
        # Page 0 is the trash page: every inactive row's block table
        # points at it, so the fused tick's scatter writes land there.
        self._cache = decode.init_paged_cache(
            cfg, self.kv_pages + 1, self.page_size)
        self._alloc = BlockAllocator(self.kv_pages, first_page=1)
        self._prefix = (RadixPrefixCache(
            self.page_size, self._alloc,
            digest_depth=_cfg.serve_affinity_digest_depth)
            if enable_prefix_cache else None)
        # --- KV tier hierarchy (T0 pool / T1 host arena / T2 store) ---
        # One page's at-rest frame: K then V bytes of [L, psz, Hkv, Dh].
        self._page_dtype = np.dtype(cfg.dtype)
        self._page_kshape = (cfg.n_layers, self.page_size,
                             decode._kv_heads(cfg), cfg.head_dim)
        self._page_k_nbytes = (int(np.prod(self._page_kshape))
                               * self._page_dtype.itemsize)
        self._page_nbytes = 2 * self._page_k_nbytes
        self._tiering = bool(_cfg.serve_kv_tiering
                             if kv_tiering is None else kv_tiering) \
            and enable_prefix_cache
        self._kv_store_dir = kv_store_dir
        self._arena: Optional[HostKVArena] = None   # lazy (worker)
        self._store: Optional[KVPageStore] = None   # lazy (worker)
        self._last_sweep = time.monotonic()
        self._last_store_gc = time.monotonic()
        self._demotions = 0
        self._promotions = 0
        self._resurrections = 0
        # Racy-read hint for submit()'s Retry-After and load_info's
        # reclaimable gauge: pool pages a pressure demotion could free
        # (tree-only T0 pages).  Worker thread refreshes it with the
        # gauges; readers tolerate staleness.
        self._demotable_hint = 0
        if self._prefix is not None:
            self._prefix.release_payload = self._release_tier_payload

        self._block_tables = np.zeros((num_slots, self._max_blocks),
                                      np.int32)
        self._pos = np.zeros((num_slots,), np.int32)
        self._tok = np.zeros((num_slots,), np.int32)
        self._slots: List[Optional[_Request]] = [None] * num_slots
        self._prefill: Optional[_PrefillState] = None

        # Counters (worker thread writes; stats() reads).
        self._tokens_generated = 0
        self._completed = 0
        self._rejected = 0
        self._cancelled = 0
        self._tick_seq = 0  # decode-tick span sampling counter
        self._committed_blocks = 0   # outstanding worst-case demand
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefix_hit_tokens = 0
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._win_t = time.monotonic()
        self._win_tokens = 0
        # Recent per-request TTFT samples (bounded ring, worker thread
        # appends) backing the ttft_p99_s gauge in load_info — the SLO
        # attainment signal the autopilot broker arbitrates on.
        self._recent_ttft = collections.deque(maxlen=256)

        self._tags = {"engine": name}
        QUEUE_GAUGE.set(0, tags=self._tags)
        OCCUPANCY_GAUGE.set(0.0, tags=self._tags)
        KV_BLOCKS_TOTAL_GAUGE.set(self.kv_pages, tags=self._tags)
        KV_BLOCKS_FREE_GAUGE.set(self.kv_pages, tags=self._tags)

    # ------------------------------------------------------------------
    # Public API

    def start(self):
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = False
            self._thread = threading.Thread(
                target=self._run, name=f"llm-engine-{self.name}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 30.0):
        """Stop the worker; outstanding requests fail with
        RuntimeError("engine stopped")."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
        err = RuntimeError("engine stopped")
        with self._cond:
            leftovers = self._scheduler.drain()
            if self._prefill is not None:
                leftovers.append(self._prefill.req)
                self._prefill = None
            self._committed_blocks = 0
            commands, self._commands = \
                list(self._commands), collections.deque()
            QUEUE_GAUGE.set(0, tags=self._tags)
        for _fn, fut in commands:
            if not fut.done():
                fut.set_exception(RuntimeError("engine stopped"))
        for req in leftovers:
            req.stream._finish(err)
        if t is not None and t.is_alive():
            # join() timed out: the worker is wedged mid-tick and still
            # OWNS the slot table, cache, and paging state.  Mutating
            # them from here would race a live thread (found by
            # RTC101); it will see _stop and exit on its own — leave
            # its state alone.
            logger.warning(
                "engine %s worker did not exit within %.1fs; leaving "
                "slot/paging state for it to tear down", self.name,
                timeout)
            return
        for s, req in enumerate(self._slots):
            if req is not None:
                req.stream._finish(err)
                self._slots[s] = None
        self._reset_paging()
        OCCUPANCY_GAUGE.set(0.0, tags=self._tags)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _blocks_for(self, prompt_len: int, max_new: int) -> int:
        return -(-(prompt_len + max_new + self._slack) // self.page_size)

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_k: int = 0,
               eos_token: Optional[int] = None, seed: int = 0,
               request_id: Optional[str] = None,
               session_id: Optional[str] = None,
               rng_state: Optional[Dict] = None) -> TokenStream:
        """Queue one prompt; returns its TokenStream immediately.

        Raises EngineOverloadedError when admission is saturated —
        reason "queue_full" (waiting line at max_queue_len) or
        "kv_exhausted" (outstanding worst-case KV page demand past the
        commit cap) — and ValueError for prompts the pool can never
        hold."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        max_new = int(self.default_max_new_tokens
                      if max_new_tokens is None else max_new_tokens)
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
        if len(prompt) + max_new > self.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceeds the engine's max_seq={self.max_seq}")
        n_blocks = self._blocks_for(len(prompt), max_new)
        if n_blocks > self.kv_pages:
            raise ValueError(
                f"request needs {n_blocks} KV pages of {self.page_size} "
                f"tokens; the pool only has {self.kv_pages}")
        # Sampling knobs are validated HERE, the single entry point: a
        # bad value surfacing later, inside the worker tick, would fail
        # every co-resident request (_fail_all), not just this one.
        temperature = float(temperature)
        top_k = int(top_k)
        if not np.isfinite(temperature) or temperature < 0:
            raise ValueError(f"temperature must be finite and >= 0, "
                             f"got {temperature}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        req = _Request(request_id or uuid.uuid4().hex[:12], prompt,
                       max_new, temperature, top_k, eos_token, seed,
                       n_blocks, session=session_id, rng_state=rng_state)
        with self._cond:
            if self._committed_blocks + n_blocks > self._commit_cap:
                self._rejected += 1
                REQUESTS_COUNTER.inc(tags={**self._tags,
                                           "status": "rejected"})
                # Retry hint from config, not a constant — and when the
                # demotion sweeper could free enough cold pages for
                # this request by its next pass, say THAT horizon (the
                # client should come back after one sweep, not after
                # the generic backoff).
                retry = max(0.05, float(_cfg.serve_kv_retry_after_s))
                if self._tiering and self._demotable_hint >= n_blocks:
                    retry = min(retry, max(
                        0.05, float(_cfg.serve_kv_tier_sweep_s)))
                raise EngineOverloadedError(
                    f"KV pool exhausted: {self._committed_blocks} pages "
                    f"of worst-case demand outstanding + {n_blocks} "
                    f"needed exceeds the commit cap "
                    f"({self._commit_cap} = factor * {self.kv_pages} "
                    f"pages); retry later",
                    reason="kv_exhausted", retry_after_s=retry)
            try:
                self._scheduler.enqueue(req)
            except EngineOverloadedError:
                self._rejected += 1
                REQUESTS_COUNTER.inc(tags={**self._tags,
                                           "status": "rejected"})
                raise
            self._committed_blocks += n_blocks
            QUEUE_GAUGE.set(self._scheduler.depth, tags=self._tags)
            self._cond.notify_all()
        self.start()
        return req.stream

    async def generate(self, prompt: Sequence[int], **kw) -> List[int]:
        """submit() + collect(): the whole generation as a list."""
        return await self.submit(prompt, **kw).collect()

    # ------------------------------------------------------------------
    # Worker commands (KV migration surface)

    def run_on_worker(self, fn, timeout: float = 30.0):
        """Run fn() on the engine worker thread between ticks and
        return its result.  The worker owns the device handle and every
        paging structure; a command is how any other thread touches
        them — same single-owner rule the tick itself relies on.
        Blocks the CALLING thread only (never the tick)."""
        import concurrent.futures as _cf
        fut: _cf.Future = _cf.Future()
        with self._cond:
            if self._stop:
                raise RuntimeError("engine stopped")
            self._commands.append((fn, fut))
            self._cond.notify_all()
        self.start()
        return fut.result(timeout)

    def _drain_commands(self):
        while True:
            with self._cond:
                if not self._commands:
                    return
                fn, fut = self._commands.popleft()
            try:
                res = fn()
            except BaseException as e:  # fail THIS command only
                if not fut.done():
                    fut.set_exception(e)
            else:
                if not fut.done():
                    fut.set_result(res)

    def kv_export(self, tokens: Sequence[int]) -> Optional[Dict]:
        """Worker command: snapshot the K/V pages of `tokens`' longest
        cached full-page prefix, page-major on host — ANY tier.  Pool
        pages are INCREF'd before the device read — an eviction racing
        the migration can drop the radix nodes but never recycle the
        pages under the wire — and stay pinned until
        kv_export_release().  Demoted pages are CRC-verified host
        bytes already and are copied synchronously (nothing to pin; an
        unreadable tier frame truncates the export there).  Returns
        {"pages" (the pinned pool pages only), "matched_tokens", "k",
        "v"} or None when nothing is cached."""
        if self._prefix is None:
            return None
        tokens = [int(t) for t in tokens]
        nodes, _ = self._prefix.match_nodes(tokens)
        usable, frames = [], {}
        for n in nodes:
            if n.tier == TIER_POOL:
                usable.append(n)
                continue
            frame = self._tier_frame(n)
            if frame is None:
                break
            frames[id(n)] = frame
            usable.append(n)
        if not usable:
            return None
        pool_pages = [n.page for n in usable if n.tier == TIER_POOL]
        for p in pool_pages:
            self._alloc.incref(p)
        try:
            if pool_pages:
                k0, v0 = decode.paged_read_pages_host(self._cache,
                                                      pool_pages)
            k = np.empty((len(usable),) + self._page_kshape,
                         self._page_dtype)
            v = np.empty_like(k)
            j = 0
            for i, n in enumerate(usable):
                if n.tier == TIER_POOL:
                    k[i], v[i] = k0[j], v0[j]
                    j += 1
                else:
                    k[i], v[i] = split_frame(
                        frames[id(n)], self._page_k_nbytes,
                        self._page_kshape, self._page_kshape,
                        self._page_dtype)
        except BaseException:
            for p in pool_pages:
                self._alloc.decref(p)
            raise
        return {"pages": pool_pages,
                "matched_tokens": len(usable) * self.page_size,
                "k": k, "v": v}

    def kv_export_release(self, pages: Sequence[int]) -> None:
        """Worker command: drop the export pins taken by kv_export —
        called only after the destination sealed (or the migration
        aborted), so the origin's pages outlive the transfer."""
        for p in pages:
            self._alloc.decref(p)
        self._update_kv_gauges()

    def kv_import(self, tokens: Sequence[int], k: np.ndarray,
                  v: np.ndarray) -> int:
        """Worker command: land migrated K/V pages (page-major
        [n, L, page_size, Hkv, Dh] host arrays for tokens' full pages)
        into freshly reserved pool pages and publish them in the radix
        cache.  Pages this replica already holds are skipped; on any
        failure the reservation is released whole — the cache is never
        left referencing a partially written page.  Returns the number
        of pages imported (0 = re-prefill instead)."""
        if self._prefix is None:
            return 0
        tokens = [int(t) for t in tokens]
        psz = self.page_size
        usable = min(len(k), len(tokens) // psz)
        have, _ = self._prefix.match(tokens, max_tokens=usable * psz)
        start = len(have)
        if start >= usable:
            return 0
        need = usable - start
        got = self._alloc.alloc(need)
        if got is None:
            # Same pressure order as admission: demote cold pages
            # before evicting shared prefixes.
            self._demote_for_pressure(need)
            got = self._alloc.alloc(need)
        if got is None \
                and self._alloc.free_pages + self._prefix.releasable() \
                >= need:
            self._prefix.evict(need)
            got = self._alloc.alloc(need)
        if got is None:
            return 0  # pool too hot to host the import: re-prefill
        try:
            self._cache = decode.paged_write_pages(
                self._cache, jnp.asarray(np.asarray(got, np.int32)),
                jnp.asarray(k[start:usable]),
                jnp.asarray(v[start:usable]))
            self._prefix.insert(tokens[:usable * psz],
                                list(have) + list(got))
        except BaseException:
            for p in got:
                self._alloc.decref(p)
            self._update_kv_gauges()
            raise
        # insert() increfs each NEW node's page; our allocation ref is
        # now redundant — the radix tree is the sole owner, exactly as
        # if these pages had been prefilled and released here.
        for p in got:
            self._alloc.decref(p)
        self._update_kv_gauges()
        return need

    def kv_hot_prefixes(self, top_k: int) -> List[List[int]]:
        """Worker command: token sequences of the hottest cached
        prefixes (drain migration walks these)."""
        if self._prefix is None:
            return []
        return self._prefix.hot_prefixes(top_k)

    # ------------------------------------------------------------------
    # KV memory hierarchy (worker thread owns every method here)

    def _tier_arena(self) -> HostKVArena:
        if self._arena is None:
            self._arena = HostKVArena(
                self._page_nbytes,
                int(_cfg.serve_kv_t1_budget_bytes), name=self.name)
        return self._arena

    def _tier_store(self) -> KVPageStore:
        if self._store is None:
            self._store = KVPageStore(self._kv_store_dir or None)
        return self._store

    def _release_tier_payload(self, payload) -> None:
        """RadixPrefixCache.release_payload hook: hand a T1 slot back
        to the arena when the tree stops owning it.  T2 entries are
        left in the store on purpose (the TTL sweep owns them — their
        persistence is what durable sessions resurrect from)."""
        if payload and payload[0] == "t1" and self._arena is not None:
            self._arena.free(payload[1])

    def _tier_frame(self, node) -> Optional[bytes]:
        """CRC-checked at-rest bytes of a demoted node, or None — a
        MISS: the caller truncates its match there and the chunk is
        re-prefilled (bit-identical by determinism).  A page is never
        imported unverified."""
        payload = node.payload
        if payload is None:
            return None
        kind, key, crc, nbytes = payload
        if kind == "t1":
            frame = (self._arena.get(key)
                     if self._arena is not None else None)
        else:
            frame = self._tier_store().get_page(key)
        if frame is None or len(frame) != nbytes \
                or frame_crc(frame) != crc:
            return None
        return frame

    def _frames_to_arrays(self, frames):
        n = len(frames)
        k = np.empty((n,) + self._page_kshape, self._page_dtype)
        v = np.empty_like(k)
        for i, fr in enumerate(frames):
            k[i], v[i] = split_frame(fr, self._page_k_nbytes,
                                     self._page_kshape,
                                     self._page_kshape,
                                     self._page_dtype)
        return k, v

    def _sweep_due(self) -> bool:
        return (self._tiering and self._prefix is not None
                and time.monotonic() - self._last_sweep
                >= max(0.05, float(_cfg.serve_kv_tier_sweep_s)))

    def _maybe_sweep_tiers(self, force: bool = False) -> int:
        """The demotion sweeper: pool pages with no decode tick in
        serve_kv_demote_idle_s move to the host arena (overflow goes
        straight to the store), arena pages idle serve_kv_t2_idle_s
        move to the store, and the store's TTL sweep ages dead entries
        out.  Runs between ticks at serve_kv_tier_sweep_s cadence;
        `force` is the test hook."""
        if not self._tiering or self._prefix is None:
            return 0
        now = time.monotonic()
        if not force and now - self._last_sweep \
                < max(0.05, float(_cfg.serve_kv_tier_sweep_s)):
            return 0
        self._last_sweep = now
        moved = self._demote_t0(self._prefix.demote_candidates(
            max(0.0, float(_cfg.serve_kv_demote_idle_s))))
        moved += self._demote_t1(max(0.0,
                                     float(_cfg.serve_kv_t2_idle_s)))
        if self._store is not None \
                and now - self._last_store_gc >= 60.0:
            self._last_store_gc = now
            self._store.sweep(float(_cfg.serve_kv_store_ttl_s))
        self._update_kv_gauges()
        return moved

    def _demote_t0(self, nodes) -> int:
        """Move tree-only pool pages (refcount 1, selected by the
        caller) into the arena — or the store when the arena budget is
        spent.  One batched device read covers the whole set; each
        node's demotion commits only after its frame landed, so a
        failed landing just leaves the page hot."""
        if not nodes:
            return 0
        k, v = decode.paged_read_pages_host(
            self._cache, [n.page for n in nodes])
        moved = 0
        for i, node in enumerate(nodes):
            frame = page_frame(k[i], v[i])
            crc = frame_crc(frame)
            slot = self._tier_arena().put(frame)
            if slot is not None:
                self._prefix.apply_demote(
                    node, TIER_HOST, ("t1", slot, crc, len(frame)))
                dest = "t1"
            else:
                fp = self._prefix.path_fp(node)
                if not self._tier_store().put_page(fp, frame):
                    continue   # nowhere to land: the page stays hot
                self._prefix.apply_demote(
                    node, TIER_STORE, ("t2", fp, crc, len(frame)))
                dest = "t2"
            moved += 1
            self._demotions += 1
            KV_DEMOTIONS_COUNTER.inc(tags={**self._tags, "to": dest})
        return moved

    def _demote_t1(self, min_idle_s: float) -> int:
        """Arena pages idle past min_idle_s move to the store (CRC
        re-verified on the way out; an unreadable slot is skipped and
        the promote path treats it as a miss)."""
        if self._arena is None:
            return 0
        moved = 0
        for node in self._prefix.demote_candidates(min_idle_s,
                                                   tier=TIER_HOST):
            _, slot, crc, nbytes = node.payload
            frame = self._arena.get(slot)
            if frame is None or frame_crc(frame) != crc:
                continue
            fp = self._prefix.path_fp(node)
            if not self._tier_store().put_page(fp, frame):
                continue
            self._prefix.apply_demote(node, TIER_STORE,
                                      ("t2", fp, crc, nbytes))
            moved += 1
            self._demotions += 1
            KV_DEMOTIONS_COUNTER.inc(tags={**self._tags, "to": "t2"})
        return moved

    def _demote_for_pressure(self, need: int) -> int:
        """Admission under memory pressure prefers DEMOTING cold
        tree-only pages (their bytes survive in a lower tier and can
        be promoted back) over EVICTING shared prefixes (their bytes
        are gone).  min_idle 0: under pressure anything tree-only is
        fair game, coldest first."""
        if not self._tiering or self._prefix is None:
            return 0
        short = need - self._alloc.free_pages
        if short <= 0:
            return 0
        return self._demote_t0(
            self._prefix.demote_candidates(0.0, limit=short))

    def kv_flush_to_store(self) -> int:
        """Worker command: demote EVERY demotable page — tree-only
        pool pages and all arena slots — straight to the store.  The
        drain/teardown path: a dying replica demotes instead of
        dropping, so its sessions resurrect anywhere from T2."""
        if not self._tiering or self._prefix is None:
            return 0
        store = self._tier_store()
        flushed = 0
        nodes = self._prefix.demote_candidates(0.0)
        if nodes:
            k, v = decode.paged_read_pages_host(
                self._cache, [n.page for n in nodes])
            for i, node in enumerate(nodes):
                frame = page_frame(k[i], v[i])
                fp = self._prefix.path_fp(node)
                if not store.put_page(fp, frame):
                    continue
                self._prefix.apply_demote(
                    node, TIER_STORE,
                    ("t2", fp, frame_crc(frame), len(frame)))
                flushed += 1
                self._demotions += 1
                KV_DEMOTIONS_COUNTER.inc(tags={**self._tags,
                                               "to": "t2"})
        flushed += self._demote_t1(0.0)
        self._update_kv_gauges()
        return flushed

    # ------------------------------------------------------------------
    # Durable sessions (store-backed checkpoint / resurrect)

    def _maybe_checkpoint_session(self, req: _Request) -> None:
        """Worker thread, called BEFORE the request's pages are
        released: publish the session's full K/V pages into the radix
        tree (the tiering sweeper then owns their cooling toward the
        store) and write the session manifest — token history plus
        sampler RNG state — to the store.  The manifest is what lets
        ANY replica resurrect the conversation: pages rejoin from the
        store by fingerprint or by re-prefill, both bit-identical."""
        if not self._tiering or req.session is None:
            return
        psz = self.page_size
        if req.tokens:
            toks = list(req.tokens)
            # The LAST sampled token was never fed back through a tick,
            # so its K/V was never written — only positions
            # [0, len(toks)-2] hold state.
            full = max(0, (len(toks) - 1) // psz)
        else:
            toks = [int(t) for t in req.prompt]
            full = len(toks) // psz   # prefill covered every position
        full = min(full, len(req.pages))
        try:
            if full and self._prefix is not None:
                self._prefix.insert(toks[:full * psz],
                                    req.pages[:full])
            man = {"tokens": [int(t) for t in toks],
                   "t": time.time(), "engine": self.name}
            if req.rng is not None:
                man["rng_state"] = req.rng.bit_generator.state
            self._tier_store().put_session(req.session, man)
        except Exception:
            # A failed checkpoint degrades durability, never the
            # request (its stream already has every token).
            logger.exception("engine %s: session %s checkpoint failed",
                             self.name, req.session)

    def session_resurrect(self, session_id: str,
                          tokens: Optional[Sequence[int]] = None
                          ) -> Optional[Dict]:
        """Worker command: restore a durable session from the store.

        Loads the manifest, then imports whatever store pages the
        local radix tree does not already cover (per-page CRC gate: an
        unreadable page stops the import there and the tail
        re-prefills — deterministic prefill makes the fallback exact,
        so resurrection never trades parity for durability).  Returns
        {"tokens", "rng_state", "imported", "cached_pages"} or None
        when no manifest exists."""
        if not self._tiering or self._prefix is None:
            return None
        man = self._tier_store().get_session(session_id)
        if man is None:
            return None
        toks = [int(t) for t in (tokens if tokens is not None
                                 else man.get("tokens") or [])]
        psz = self.page_size
        usable = len(toks) // psz
        nodes, _ = self._prefix.match_nodes(toks)
        depth_lo = len(nodes)
        imported = 0
        if depth_lo < usable:
            fps = prefix_fingerprints(toks, psz, usable)
            frames = []
            store = self._tier_store()
            for d in range(depth_lo, usable):
                frame = store.get_page(fps[d])
                if frame is None or len(frame) != self._page_nbytes:
                    break
                frames.append(frame)
            if frames:
                imported = self._import_store_frames(toks, nodes,
                                                     frames)
        self._resurrections += 1
        RESURRECTIONS_COUNTER.inc(tags=self._tags)
        self._update_kv_gauges()
        return {"tokens": man.get("tokens"),
                "rng_state": man.get("rng_state"),
                "imported": imported,
                "cached_pages": depth_lo}

    def _import_store_frames(self, toks, path_nodes, frames) -> int:
        """Land store frames below an existing (any-tier) matched
        path: reserve pool pages, splice, publish.  Existing path
        nodes pass page=None through insert(), so a demoted ancestor
        keeps its payload instead of adopting garbage."""
        psz = self.page_size
        need = len(frames)
        got = self._alloc.alloc(need)
        if got is None:
            self._demote_for_pressure(need)
            got = self._alloc.alloc(need)
        if got is None \
                and self._alloc.free_pages + self._prefix.releasable() \
                >= need:
            self._prefix.evict(need)
            got = self._alloc.alloc(need)
        if got is None:
            return 0   # pool too hot: resurrect by re-prefill instead
        try:
            k, v = self._frames_to_arrays(frames)
            self._cache = decode.paged_write_pages(
                self._cache, jnp.asarray(np.asarray(got, np.int32)),
                jnp.asarray(k), jnp.asarray(v))
            depth_hi = len(path_nodes) + need
            self._prefix.insert(toks[:depth_hi * psz],
                                [None] * len(path_nodes) + list(got))
        except BaseException:
            for p in got:
                self._alloc.decref(p)
            self._update_kv_gauges()
            raise
        for p in got:
            self._alloc.decref(p)   # the tree's refs own them now
        return need

    def load_info(self) -> Dict[str, int]:
        """The autoscaler's saturation gauges, as plain field reads —
        polled every control-loop tick, so no EngineStats construction
        and no rate-window math on this path."""
        info = {"queue_depth": self._scheduler.depth
                + (1 if self._prefill is not None else 0),
                "active_slots": sum(r is not None for r in self._slots),
                "num_slots": self.num_slots,
                "kv_blocks_total": self.kv_pages,
                "kv_blocks_free": self._alloc.free_pages}
        if self._prefix is not None:
            tn = self._prefix.tier_nodes
            info["kv_tier_pages"] = {"t0": tn[0], "t1": tn[1],
                                     "t2": tn[2]}
            info["kv_demotable"] = self._demotable_hint
            # What admission can ACTUALLY claim: the free list plus
            # everything pressure demotion would surrender.  The
            # autoscaler reads this instead of kv_blocks_free so idle
            # sessions parked in the pool never look like saturation
            # (no phantom scale-ups).
            info["kv_blocks_reclaimable"] = (self._alloc.free_pages
                                             + self._demotable_hint)
        if self._recent_ttft:
            # p99 over the recent ring (snapshot first: the worker
            # thread appends concurrently).
            samples = sorted(self._recent_ttft)
            info["ttft_p99_s"] = samples[
                min(len(samples) - 1, int(len(samples) * 0.99))]
        if self._prefix is not None and _cfg.serve_affinity:
            try:
                # Racy-but-safe read of the worker-owned digest index
                # (best-effort gauge: a poll that loses the race just
                # publishes the previous digest next tick).
                info["kv_digest"] = {
                    "page": self.page_size,
                    "roots": self._prefix.digest(
                        _cfg.serve_affinity_digest_top_k)}
            except RuntimeError:
                pass  # index mutated mid-iteration; skip this sample
        return info

    def stats(self) -> EngineStats:
        now = time.monotonic()
        win = now - self._win_t
        tps = self._win_tokens / win if win > 0.2 else 0.0
        return EngineStats(
            queue_depth=self._scheduler.depth
            + (1 if self._prefill is not None else 0),
            active_slots=sum(r is not None for r in self._slots),
            num_slots=self.num_slots,
            tokens_generated=self._tokens_generated,
            requests_completed=self._completed,
            requests_rejected=self._rejected,
            requests_cancelled=self._cancelled,
            tokens_per_sec=round(tps, 2),
            uptime_s=round(now - self._started_t, 3),
            page_size=self.page_size,
            kv_blocks_total=self.kv_pages,
            kv_blocks_free=self._alloc.free_pages,
            prefix_cache_hits=self._prefix_hits,
            prefix_cache_misses=self._prefix_misses,
            prefix_hit_tokens=self._prefix_hit_tokens,
            spec_drafted_tokens=self._spec_drafted,
            spec_accepted_tokens=self._spec_accepted,
            kv_t1_pages=(self._prefix.tier_nodes[TIER_HOST]
                         if self._prefix is not None else 0),
            kv_t2_pages=(self._prefix.tier_nodes[TIER_STORE]
                         if self._prefix is not None else 0),
            kv_demotions=self._demotions,
            kv_promotions=self._promotions,
            session_resurrections=self._resurrections)

    # ------------------------------------------------------------------
    # Worker thread

    def _run(self):
        try:
            self._warm_kernels()
        except Exception as e:
            logger.exception("engine %s kernel warmup failed", self.name)
            self._fail_all(e)
        while True:
            with self._cond:
                # The idle wait must ALSO break for a due tier sweep:
                # an engine with no work is exactly the one whose pages
                # are going cold, and sweeps are what move them out of
                # the decode pool.
                while not self._stop and not self._has_work_locked() \
                        and not self._sweep_due():
                    self._cond.wait(timeout=0.1)
                if self._stop:
                    return
            # Commands (KV export/import) run BETWEEN ticks: they own
            # the device + paging state for their duration, and their
            # failures are their caller's, never the batch's.
            self._drain_commands()
            try:
                self._maybe_sweep_tiers()
                self._admit_one_chunk()
                self._decode_tick()
            except Exception as e:  # engine-level fault: fail fast,
                logger.exception("engine %s tick failed", self.name)
                self._fail_all(e)

    def _warm_kernels(self):
        """Compile the fused tick kernels at worker startup, against the
        engine's own (still empty) state: every write lands in the trash
        page, so this is free of side effects — and the first real
        request never pays XLA compilation of the decode tick, nor does
        the first DRAFT pay the verify kernel's (it would otherwise land
        mid-generation, a latency spike the bench used to misreport as
        speculation overhead)."""
        tok = jnp.zeros((self.num_slots,), jnp.int32)
        pos = jnp.zeros((self.num_slots,), jnp.int32)
        bt = jnp.asarray(self._block_tables)
        _, _, self._cache = _paged_tick(
            self.params, tok, pos, self._cache, bt, self.cfg,
            with_logits=False)
        if self.speculate_k:
            chunk = jnp.zeros((self.num_slots, 1 + self.speculate_k),
                              jnp.int32)
            _, _, self._cache = _paged_verify(
                self.params, chunk, pos, self._cache, bt, self.cfg,
                with_logits=False)
        # ...and the standard-width prefill chunk (row 0's table is all
        # trash while nothing is admitted).
        _, self._cache = _prefill_chunk(
            self.params, jnp.zeros((1, self.prefill_chunk), jnp.int32),
            jnp.int32(0), self._cache, bt[:1], self.cfg)

    def _has_work_locked(self) -> bool:
        return (self._scheduler.depth > 0 or self._prefill is not None
                or bool(self._commands)
                or any(r is not None for r in self._slots))

    def _free_slot(self) -> Optional[int]:
        reserved = self._prefill.slot if self._prefill else -1
        for s, r in enumerate(self._slots):
            if r is None and s != reserved:
                return s
        return None

    def _release_pages(self, req: _Request):
        for p in req.pages:
            self._alloc.decref(p)
        req.pages = []
        self._update_kv_gauges()

    def _try_reserve(self, req: _Request):
        """Prefix-match + page reservation for one request.  Returns
        (pages, matched_tokens) or None when the pool can't cover the
        request right now (caller requeues and retries after evictions
        free pages).

        Tier-aware: the match walks ALL tiers; demoted nodes on the
        matched path are PROMOTED — their frames are CRC-verified on
        host FIRST (an unreadable frame truncates the match there and
        the tail re-prefills, bit-identical by determinism), then
        spliced into freshly reserved pool pages inside the same
        all-or-nothing reservation that admits the request."""
        L = len(req.prompt)
        matched_nodes: List = []
        promote: List = []   # (node, verified frame) in path order
        if self._prefix is not None:
            # Cap at L-1: at least one prompt token must run through
            # tail prefill — logits come from computation, not cache.
            nodes, _ = self._prefix.match_nodes(req.prompt,
                                                max_tokens=L - 1)
            for n in nodes:
                if n.tier == TIER_POOL:
                    matched_nodes.append(n)
                    continue
                if not self._tiering:
                    break
                frame = self._tier_frame(n)
                if frame is None:
                    break   # dead payload: re-prefill from here on
                matched_nodes.append(n)
                promote.append((n, frame))
        matched_tok = len(matched_nodes) * self.page_size
        pool_pages = [n.page for n in matched_nodes
                      if n.tier == TIER_POOL]
        # Hold the matched pool pages BEFORE any demotion or eviction
        # can run: evict() may drop their tree nodes, and only our refs
        # keep the pages from being recycled under us.  (The extra ref
        # also makes them ineligible for pressure demotion below.)
        for p in pool_pages:
            self._alloc.incref(p)
        need = req.n_blocks - len(pool_pages)
        got = self._alloc.alloc(need)
        if got is None:
            # Pressure order: demote cold tree-only pages first (their
            # bytes survive in a lower tier), evict shared prefixes
            # only when that still doesn't cover the reservation.
            self._demote_for_pressure(need)
            got = self._alloc.alloc(need)
        if got is None and promote:
            # About to fall back to eviction, which may drop the very
            # tiered leaves queued for promotion (a demoted node holds
            # no pinnable pool page).  Truncate the match at the first
            # demoted node — the tail re-prefills — rather than let
            # promote() run against an orphaned node.
            cut = matched_nodes.index(promote[0][0])
            for n in matched_nodes[cut:]:
                if n.tier == TIER_POOL:
                    self._alloc.decref(n.page)
            matched_nodes = matched_nodes[:cut]
            promote = []
            matched_tok = len(matched_nodes) * self.page_size
            pool_pages = [n.page for n in matched_nodes]
            need = req.n_blocks - len(pool_pages)
        if got is None and self._prefix is not None \
                and self._alloc.free_pages + self._prefix.releasable() \
                >= need:
            # Evict only when reclaim can actually cover the request —
            # an unsatisfiable reservation must not wipe the prefix
            # cache for nothing (the request waits for resident rows to
            # finish instead).
            self._prefix.evict(need)
            got = self._alloc.alloc(need)
        if got is None:
            for p in pool_pages:
                self._alloc.decref(p)
            return None
        if promote:
            try:
                k, v = self._frames_to_arrays([f for _, f in promote])
                landing = got[:len(promote)]
                self._cache = decode.paged_write_pages(
                    self._cache,
                    jnp.asarray(np.asarray(landing, np.int32)),
                    jnp.asarray(k), jnp.asarray(v))
            except BaseException:
                for p in got:
                    self._alloc.decref(p)
                for p in pool_pages:
                    self._alloc.decref(p)
                self._update_kv_gauges()
                raise
            for (node, _), page in zip(promote, landing):
                # The page's allocation ref becomes the TREE's ref;
                # the request then takes its own, same as a pool hit.
                self._prefix.promote(node, page)
                self._alloc.incref(page)
            self._promotions += len(promote)
            KV_PROMOTIONS_COUNTER.inc(len(promote), tags=self._tags)
            got = got[len(promote):]
        if matched_tok > 0:
            self._prefix_hits += 1
            self._prefix_hit_tokens += matched_tok
            PREFIX_HITS_COUNTER.inc(tags=self._tags)
        else:
            self._prefix_misses += 1
            PREFIX_MISSES_COUNTER.inc(tags=self._tags)
        req.pages = [n.page for n in matched_nodes] + got
        req.prefix_hit_tokens = matched_tok
        self._update_kv_gauges()
        return req.pages, matched_tok

    def _admit_one_chunk(self):
        """Advance admission by AT MOST one prefill chunk (the bound on
        how long a tick's decode can be delayed by an arrival)."""
        if self._prefill is None:
            slot = self._free_slot()
            if slot is None:
                return
            with self._cond:
                req = self._scheduler.next_request()
                QUEUE_GAUGE.set(self._scheduler.depth, tags=self._tags)
            while req is not None and req.stream.cancelled:
                self._finish_request(req, "cancelled")
                with self._cond:
                    req = self._scheduler.next_request()
                    QUEUE_GAUGE.set(self._scheduler.depth,
                                    tags=self._tags)
            if req is None:
                return
            reserved = self._try_reserve(req)
            if reserved is None:
                # KV-starved: requests resident in the pool will finish
                # and free pages; FCFS order is preserved by putting
                # the head back.
                with self._cond:
                    self._scheduler.requeue_head(req)
                    QUEUE_GAUGE.set(self._scheduler.depth,
                                    tags=self._tags)
                return
            pages, matched_tok = reserved
            bt_row = np.zeros((self._max_blocks,), np.int32)
            bt_row[:len(pages)] = pages
            # _prefill writes stay under _cond: stop() tears the field
            # down under _cond after a join that may have TIMED OUT
            # with this thread still mid-tick, so the handoff must be
            # a real critical section, not owner-confinement.
            with self._cond:
                self._prefill = _PrefillState(req, slot, matched_tok,
                                              bt_row)
            # TTFT stage 1 of 3 — queue: submit() to admission (pages
            # reserved, prefill about to start).
            _span_for(req, "engine.queue", req.submit_t,
                      time.monotonic() - req.submit_t,
                      args={"request_id": req.id,
                            "prefix_hit_tokens": matched_tok})

        st = self._prefill
        req = st.req
        if req.stream.cancelled:
            with self._cond:
                self._prefill = None
            self._release_pages(req)
            self._finish_request(req, "cancelled")
            return
        L = len(req.prompt)
        start = st.next_start
        width = min(self.prefill_chunk, self._s_virt - start)
        real = req.prompt[start:start + width]
        chunk = np.zeros((1, width), np.int32)
        chunk[0, :len(real)] = real
        logits, self._cache = _prefill_chunk(
            self.params, jnp.asarray(chunk), jnp.int32(start),
            self._cache, jnp.asarray(st.bt_row[None, :]), self.cfg)
        st.next_start = start + width
        st.chunks += 1
        if st.next_start < L:
            return  # more chunks to go; decode proceeds meanwhile

        # Prefill complete: sample the first token from the last REAL
        # column of the final chunk (pad columns carry garbage).
        with self._cond:
            self._prefill = None
        t_fc = time.monotonic()
        # TTFT stage 2 of 3 — prefill: admission to the last chunk's
        # dispatch (chunk count makes chunked-prefill interleaving
        # visible against concurrent decode ticks).
        _span_for(req, "engine.prefill", st.t0, t_fc - st.t0,
                  args={"request_id": req.id, "chunks": st.chunks,
                        "prompt_tokens": L,
                        "prefix_hit_tokens": req.prefix_hit_tokens})
        if self._prefix is not None:
            # The request's FULL prompt pages now hold final K/V (decode
            # writes start at column L, outside any full prompt page) —
            # publish them for future prompts to share.  Already-cached
            # chunks are no-ops; this request's duplicates stay private.
            self._prefix.insert(req.prompt,
                                req.pages[:L // self.page_size])
        row = np.asarray(logits[0, len(real) - 1])
        first = self._sample_host(row, req)
        now = time.monotonic()
        # TTFT stage 3 of 3 — first tick: forcing the prefill logits
        # off-device + sampling the first token.  queue + prefill +
        # first_tick sums to submit→first-token, so `rt trace` derives
        # the TTFT breakdown instead of guessing.
        _span_for(req, "engine.first_tick", t_fc, now - t_fc,
                  args={"request_id": req.id})
        if req.eos_token is not None and first == req.eos_token:
            req.tokens = list(req.prompt) + [first]
            self._maybe_checkpoint_session(req)
            self._release_pages(req)
            self._finish_request(req, "completed")
            return
        if req.max_new_tokens == 1:
            # Nothing left to decode: never joins the batch.
            self._emit(req, first, now)
            req.tokens = list(req.prompt) + [first]
            self._maybe_checkpoint_session(req)
            self._release_pages(req)
            self._finish_request(req, "completed")
            return
        # Join the decode batch BEFORE the token is emitted: a consumer
        # woken by its first token must observe the request as an
        # active slot, not a phantom.  Publishing the block-table row is
        # the activation — from the next tick on, the fused scatter
        # writes into this request's pages instead of the trash page.
        self._block_tables[st.slot] = st.bt_row
        self._pos[st.slot] = L
        self._tok[st.slot] = first
        req.tokens = list(req.prompt) + [first]
        self._slots[st.slot] = req
        self._update_occupancy()
        self._emit(req, first, now)

    def _decode_tick(self):
        actives = [s for s in range(self.num_slots)
                   if self._slots[s] is not None]
        if not actives:
            return
        # Sample 1/N ticks as engine.decode_tick spans: the tick runs
        # thousands of times per second, so recording every one would
        # be pure ring churn; a sampled span still shows batch width
        # and tick latency against prefill/transfer activity.  Batch-
        # level, so no single request's trace claims it.
        sample = _cfg.trace_decode_tick_sample
        self._tick_seq += 1
        t_tick = (time.monotonic()
                  if sample > 0 and self._tick_seq % sample == 0
                  and _tracing.enabled() else None)
        self._decode_tick_inner(actives)
        if t_tick is not None:
            _tracing.record("engine", "engine.decode_tick",
                            time.time() - (time.monotonic() - t_tick),
                            time.monotonic() - t_tick,
                            args={"batch": len(actives),
                                  "sampled_1_in": sample})

    def _decode_tick_inner(self, actives):
        spec_drafts: Dict[int, List[int]] = {}
        if self.speculate_k:
            for s in actives:
                req = self._slots[s]
                if req.temperature == 0 and not req.stream.cancelled:
                    d = _lookup_draft(req, self.speculate_ngram,
                                      self.speculate_k)
                    if d:
                        spec_drafts[s] = d
        if spec_drafts:
            self._verify_tick(actives, spec_drafts)
        else:
            self._plain_tick(actives)

    def _plain_tick(self, actives):
        sample_rows = [s for s in actives
                       if self._slots[s].temperature > 0]
        sampled, logits, self._cache = _paged_tick(
            self.params, jnp.asarray(self._tok), jnp.asarray(self._pos),
            self._cache, jnp.asarray(self._block_tables), self.cfg,
            with_logits=bool(sample_rows))
        sampled = np.asarray(sampled)
        logits_np, row_of = self._ship_sample_logits(logits, sample_rows)
        now = time.monotonic()
        for s in actives:
            req = self._slots[s]
            if req.stream.cancelled:
                self._evict(s, "cancelled")
                continue
            if req.temperature > 0:
                t = _host_sample(logits_np[row_of[s]], req.temperature,
                                 req.top_k, req.rng)
            else:
                t = int(sampled[s])
            self._advance(s, req, [t], now)

    def _verify_tick(self, actives, spec_drafts):
        """One fused paged_chunk_step verifying every row's pending
        token + drafts; per-row longest-matching-prefix acceptance turns
        idle verify bandwidth into extra tokens without ever changing
        the greedy output (accepted drafts EQUAL the argmax chain by
        construction)."""
        k = self.speculate_k
        chunk = np.zeros((self.num_slots, 1 + k), np.int32)
        chunk[:, 0] = self._tok
        for s, d in spec_drafts.items():
            chunk[s, 1:1 + len(d)] = d
        sample_rows = [s for s in actives
                       if self._slots[s].temperature > 0]
        preds, logits0, self._cache = _paged_verify(
            self.params, jnp.asarray(chunk), jnp.asarray(self._pos),
            self._cache, jnp.asarray(self._block_tables), self.cfg,
            with_logits=bool(sample_rows))
        preds = np.asarray(preds)
        logits_np, row_of = self._ship_sample_logits(logits0, sample_rows)
        now = time.monotonic()
        for s in actives:
            req = self._slots[s]
            if req.stream.cancelled:
                self._evict(s, "cancelled")
                continue
            if req.temperature > 0:
                t = _host_sample(logits_np[row_of[s]], req.temperature,
                                 req.top_k, req.rng)
                self._advance(s, req, [t], now)
                continue
            d = spec_drafts.get(s, [])
            m = 0
            while m < len(d) and preds[s, m] == d[m]:
                m += 1
            # The bonus prediction always rides along, so produced
            # length is m+1; cap so the row never exceeds max_new.
            m = min(m, req.max_new_tokens - req.emitted - 1)
            self._spec_drafted += len(d)
            self._spec_accepted += m
            if m:
                SPEC_ACCEPTED_COUNTER.inc(m, tags=self._tags)
            self._advance(s, req, list(d[:m]) + [int(preds[s, m])], now)

    def _ship_sample_logits(self, logits, sample_rows):
        """Host transfer scales with the SAMPLING rows, not the whole
        pool: one temperature>0 request must not ship
        [num_slots, vocab] off-device every tick."""
        if not sample_rows:
            return None, None
        logits_np = np.asarray(
            logits[jnp.asarray(np.asarray(sample_rows, np.int32))])
        return logits_np, {s: i for i, s in enumerate(sample_rows)}

    def _advance(self, slot: int, req: _Request, produced: List[int],
                 now: float):
        """Commit one row's tick outcome: len(produced) tokens (1
        normally; accepted drafts + bonus under speculation), emitted in
        order with EOS / max_new eviction exactly as if they had been
        produced one tick at a time."""
        self._pos[slot] += len(produced)
        self._tok[slot] = produced[-1]
        req.tokens.extend(produced)
        for t in produced:
            if req.eos_token is not None and t == req.eos_token:
                self._evict(slot, "completed")
                return
            self._emit(req, t, now)
            if req.emitted >= req.max_new_tokens:
                self._evict(slot, "completed")
                return

    def _sample_host(self, row_logits: np.ndarray, req: _Request) -> int:
        if req.temperature > 0:
            return _host_sample(row_logits, req.temperature, req.top_k,
                                req.rng)
        return int(row_logits.argmax())

    def _emit(self, req: _Request, token: int, now: float):
        req.emitted += 1
        if req.first_token_t is None:
            req.first_token_t = now
            TTFT_HISTOGRAM.observe(now - req.submit_t, tags=self._tags)
            self._recent_ttft.append(now - req.submit_t)
        else:
            ITL_HISTOGRAM.observe(now - req.last_token_t,
                                  tags=self._tags)
        req.last_token_t = now
        self._tokens_generated += 1
        self._win_tokens += 1
        TOKENS_COUNTER.inc(tags=self._tags)
        if now - self._win_t >= 0.5:
            THROUGHPUT_GAUGE.set(
                self._win_tokens / (now - self._win_t),
                tags=self._tags)
            self._win_t = now
            self._win_tokens = 0
        req.stream._push(token)

    def _evict(self, slot: int, status: str):
        """Eviction is pure accounting: point the row back at the trash
        page and decref its pages.  No device work — stale K/V in a
        recycled page is always overwritten before an unmasked read
        (prefill covers the tail from its start column; decode writes a
        column before attending to it), which is what makes page
        recycling free compared to the old whole-row zeroing pass."""
        req = self._slots[slot]
        self._slots[slot] = None
        self._pos[slot] = 0
        self._tok[slot] = 0
        self._block_tables[slot, :] = 0
        # Durable sessions checkpoint BEFORE the pages are released —
        # publishing them into the radix tree needs the refs alive.
        self._maybe_checkpoint_session(req)
        self._release_pages(req)
        self._update_occupancy()
        self._finish_request(req, status)

    def _finish_request(self, req: _Request, status: str):
        if status == "cancelled":
            self._cancelled += 1
        else:
            self._completed += 1
        with self._cond:
            self._committed_blocks = max(
                0, self._committed_blocks - req.n_blocks)
        REQUESTS_COUNTER.inc(tags={**self._tags, "status": status})
        req.stream._finish()

    def _update_occupancy(self):
        OCCUPANCY_GAUGE.set(
            sum(r is not None for r in self._slots) / self.num_slots,
            tags=self._tags)

    def _update_kv_gauges(self):
        KV_BLOCKS_FREE_GAUGE.set(self._alloc.free_pages, tags=self._tags)
        if self._prefix is not None:
            for tier, count in zip(("t0", "t1", "t2"),
                                   self._prefix.tier_nodes):
                KV_TIER_PAGES_GAUGE.set(
                    count, tags={**self._tags, "tier": tier})
            if self._tiering:
                self._demotable_hint = self._prefix.releasable()

    def _reset_paging(self):
        self._alloc = BlockAllocator(self.kv_pages, first_page=1)
        if self._prefix is not None:
            self._prefix = RadixPrefixCache(
                self.page_size, self._alloc,
                digest_depth=_cfg.serve_affinity_digest_depth)
            self._prefix.release_payload = self._release_tier_payload
        if self._arena is not None:
            self._arena.close()
            self._arena = None
        self._block_tables[:] = 0
        self._update_kv_gauges()

    def _fail_all(self, err: BaseException):
        with self._cond:
            pf, self._prefill = self._prefill, None
            leftovers = self._scheduler.drain()
            self._committed_blocks = 0
            commands, self._commands = \
                list(self._commands), collections.deque()
            QUEUE_GAUGE.set(0, tags=self._tags)
        for _fn, fut in commands:
            if not fut.done():
                fut.set_exception(err)
        if pf is not None:
            pf.req.stream._finish(err)
        for req in leftovers:
            req.stream._finish(err)
        for s in range(self.num_slots):
            req = self._slots[s]
            if req is not None:
                self._slots[s] = None
                req.stream._finish(err)
        self._pos[:] = 0
        self._tok[:] = 0
        # Rebuild device state: the donated cache may be mid-flight.
        self._cache = decode.init_paged_cache(
            self.cfg, self.kv_pages + 1, self.page_size)
        self._reset_paging()
        self._update_occupancy()
