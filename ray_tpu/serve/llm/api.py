"""LLMServer: the serve deployment wrapping a GenerationEngine.

One replica = one engine = one chip's KV-slot pool.  Three surfaces:

  * handle.generate.remote(tokens, ...)          -> full token list
  * handle.options("stream").stream(tokens, ...) -> ServeResponseStream
    (token at a time, through the replica streaming transport; the
    options() spelling is needed because the method is literally named
    "stream", which shadows DeploymentHandle.stream)
  * HTTP POST {route}/  body {"tokens": [...], ...}  -> JSON; with
    Accept: text/event-stream (or "stream": true) the proxy emits SSE
    events, one token per event, as they are generated.

Engine overload surfaces as EngineOverloadedError on handles and as
HTTP 503 with Retry-After through the proxy (backpressure, not
buffering).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu._private.config import GLOBAL_CONFIG as _cfg
from ray_tpu.serve.exceptions import resumable
from ray_tpu.serve.llm import kv_transfer
from ray_tpu.serve.llm.engine import GenerationEngine
from ray_tpu.serve.llm.scheduler import EngineOverloadedError

_GEN_KEYS = ("max_new_tokens", "temperature", "top_k", "eos_token",
             "seed")


def _resume_tokens(items) -> List[int]:
    """Delivered items from a failover cursor -> token ints (handle
    streams yield bare ints, the SSE path yields {"token": t} events)."""
    out = []
    for it in items or []:
        out.append(int(it["token"]) if isinstance(it, dict) else int(it))
    return out


class LLMServer:
    """Deployment class hosting one continuous-batching engine.

    `model_loader` is a zero-arg callable returning (params, cfg) —
    a callable (not the weights) so the deployment pickles small and
    the params are materialized inside the replica process, resident
    next to its chip.  `engine_config` feeds GenerationEngine knobs
    (num_slots, max_seq, prefill_chunk, max_queue_len, ...)."""

    def __init__(self, model_loader, engine_config: Optional[Dict] = None,
                 default_generation: Optional[Dict] = None):
        params, cfg = model_loader()
        self._defaults = dict(default_generation or {})
        self.engine = GenerationEngine(params, cfg,
                                       **(engine_config or {}))
        self.engine.start()

    def _gen_kwargs(self, overrides: Dict[str, Any]) -> Dict[str, Any]:
        kw = dict(self._defaults)
        kw.update({k: v for k, v in overrides.items() if k in _GEN_KEYS})
        unknown = set(overrides) - set(_GEN_KEYS)
        if unknown:
            raise TypeError(f"unknown generation options: {sorted(unknown)}")
        return kw

    async def generate(self, tokens: Sequence[int], **overrides
                       ) -> List[int]:
        """Full generation for one prompt (continuous-batched under the
        hood with every other in-flight request)."""
        return await self.engine.generate(
            tokens, **self._gen_kwargs(overrides))

    def _trim_for_resume(self, tokens: Sequence[int], kw: Dict,
                         _resume: Optional[Dict]):
        """Failover resume: re-anchor the prompt at the cursor — prompt
        becomes original + delivered tokens (the prefix cache makes the
        re-prefill cheap) and the token budget shrinks by what was
        already delivered, so a greedy resumed stream yields EXACTLY
        the remaining tokens of the uninterrupted stream.  Returns
        (tokens, remaining_budget); remaining <= 0 means the stream was
        already complete at the cursor."""
        delivered = _resume_tokens((_resume or {}).get("items"))
        if not delivered:
            return list(tokens), 1
        max_new = kw.get("max_new_tokens")
        if max_new is None:
            max_new = self.engine.default_max_new_tokens
        remaining = int(max_new) - len(delivered)
        eos = kw.get("eos_token")
        if eos is not None and delivered[-1] == int(eos):
            remaining = 0  # the stream had already hit EOS
        kw["max_new_tokens"] = max(1, remaining)
        return list(tokens) + delivered, remaining

    @resumable
    async def stream(self, tokens: Sequence[int], _resume=None,
                     **overrides):
        """Token-streaming generation: an async generator, consumed
        through the serve streaming transport
        (handle.options("stream").stream(...) client-side, SSE over
        HTTP).

        Resumable (`_resume` carries the router's failover cursor):
        after a replica death the stream continues on a healthy replica
        with only the undelivered suffix — bit-identical for greedy
        (temperature=0) requests; sampled requests resume on a fresh
        RNG stream past the cursor (documented parity caveat)."""
        session = overrides.pop("session", None) \
            or (_resume or {}).get("session")
        kw = self._gen_kwargs(overrides)
        tokens, remaining = self._trim_for_resume(tokens, kw, _resume)
        if remaining <= 0:
            return
        rng_state = await self._prepare_kv(_resume, tokens, session)
        stream = self.engine.submit(tokens, session_id=session,
                                    rng_state=rng_state, **kw)
        try:
            async for tok in stream:
                yield int(tok)
        finally:
            # Early close (client cancelled / disconnected): free the
            # engine slot instead of generating into a dead buffer.
            stream.cancel()

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats().to_dict()

    def autoscale_metrics(self) -> Dict[str, Any]:
        """Saturation gauges for the serve controller's autoscaler
        (picked up via the replica's get_autoscale_metrics): decode
        queue depth, slot occupancy, and KV page headroom — so scaling
        tracks what the ENGINE is actually short of, not just the
        request count.  With affinity on the gauges also carry the
        engine's prefix digest (kv_digest, set by load_info) and this
        replica's migration pull address (kv_rdv) — the broadcast that
        already reaches the router teaches it both WHERE prefixes live
        and how to ship their pages, with zero extra RPCs."""
        info = self.engine.load_info()
        if _cfg.serve_affinity:
            rdv = kv_transfer.rendezvous(self.engine)
            if rdv is not None:
                info["kv_rdv"] = rdv
        return info

    async def _maybe_pull_kv(self, _resume: Optional[Dict],
                             tokens: Sequence[int]) -> int:
        """A failover cursor names the dead stream's origin replica
        (kv_origin): pull its committed pages for prompt + delivered
        tokens before submitting, so the resume's prefill collapses to
        a prefix-cache hit.  Best-effort by design — any failure means
        re-prefill, never a corrupt cache (pull_kv_pages's contract).
        Trust: kv_origin only ever arrives via the router, which
        validates client-replayed cursors against its own membership
        view (ReplicaSet._trusted_rdv) — this replica never dials an
        address a client invented."""
        rdv = (_resume or {}).get("kv_origin")
        if not rdv or not _cfg.serve_affinity:
            return 0
        mine = kv_transfer.rendezvous(self.engine)
        if mine is not None and mine == rdv:
            return 0  # resumed onto the origin itself: pages already here
        return await kv_transfer.pull_kv_pages(rdv, tokens, self.engine)

    async def _prepare_kv(self, _resume: Optional[Dict],
                          tokens: Sequence[int],
                          session: Optional[str]) -> Optional[Dict]:
        """Pre-submit KV warm-up, cheapest source first: a live origin
        pull (failover cursor), then the durable-session store.  The
        store path is what makes a session resurrect ANYWHERE — the
        origin can be minutes dead, any replica on the host imports its
        pages from T2 and the rest re-prefills bit-identically.
        Returns the session's checkpointed sampler state (None for
        greedy sessions or when nothing resurrected)."""
        try:
            await self._maybe_pull_kv(_resume, tokens)
        except Exception:
            pass  # best-effort: re-prefill covers it
        rng_state = None
        if session and _cfg.serve_kv_tiering:
            try:
                res = await kv_transfer._on_worker(
                    self.engine,
                    lambda: self.engine.session_resurrect(session,
                                                          tokens))
            except Exception:
                res = None
            if res is not None:
                rng_state = res.get("rng_state")
        return rng_state

    # -- KV migration control surface (router / controller RPCs) -------

    def kv_rendezvous(self) -> Optional[Dict]:
        """Where a peer can pull this replica's KV pages from."""
        return kv_transfer.rendezvous(self.engine)

    def kv_drain_manifest(self, top_k: int = 8) -> Optional[Dict]:
        """Drain handoff, origin side: this replica's pull address plus
        the token paths of its hottest cached prefixes.  The controller
        fetches this from a DRAINING replica and hands it to the chosen
        survivor's kv_pull_from — the survivor pulls, so teardown
        ordering stays trivial (the origin just keeps serving exports
        until its pages have been copied out).

        With tiering on, every demotable page is flushed to the store
        FIRST: a dying replica demotes instead of dropping, so even if
        no survivor ever pulls (or this process is killed mid-drain
        afterwards), its sessions resurrect anywhere from T2."""
        try:
            self.engine.run_on_worker(self.engine.kv_flush_to_store,
                                      timeout=10.0)
        except Exception:
            pass  # flush is belt-and-braces; the pull path still runs
        rdv = kv_transfer.rendezvous(self.engine)
        if rdv is None:
            return None
        prefixes = self.engine.run_on_worker(
            lambda: self.engine.kv_hot_prefixes(top_k))
        prefixes = [p for p in prefixes
                    if len(p) >= _cfg.serve_kv_min_migrate_pages
                    * self.engine.page_size]
        if not prefixes:
            return None
        return {"rdv": rdv, "prefixes": prefixes}

    async def kv_pull_from(self, manifest: Dict) -> int:
        """Drain handoff, survivor side: pull each offered prefix from
        the draining origin.  Copies, not moves — the origin's pages
        are untouched, so an un-drain mid-flight cannot double-count
        anything; its copies simply age out of both caches normally."""
        total = 0
        for toks in (manifest or {}).get("prefixes", []):
            total += await kv_transfer.pull_kv_pages(
                manifest["rdv"], toks, self.engine)
        return total

    def trace_spans(self, prefix: str = "engine.") -> List[Dict]:
        """Spans from THIS replica process's trace ring (the bench's
        TTFT-attribution probe: engine.queue / engine.prefill /
        engine.first_tick live here, not in the client process)."""
        from ray_tpu._private import tracing as _tracing
        return [e for e in _tracing.ring().snapshot(clear=False)
                if str(e.get("name", "")).startswith(prefix)]

    def check_health(self):
        if not self.engine.running:
            raise RuntimeError("generation engine worker is not running")

    def __del__(self):
        try:
            self.engine.stop(timeout=5.0)
        except Exception:
            pass

    # -- HTTP entry point (proxy) --------------------------------------

    @resumable
    async def __call__(self, request, _resume=None):
        """POST JSON {"tokens": [ints], "max_new_tokens"?, "temperature"?,
        "top_k"?, "eos_token"?, "seed"?}.

        Plain: {"tokens": [...]} JSON in one shot.  With
        `Accept: text/event-stream` or `?stream=1` the PROXY routes the
        call through the streaming transport and this returns an async
        generator — one `data: {"token": t}` SSE event per generated
        token (the detection rule here must mirror the proxy's, which
        decides before the replica is ever called).  SSE requests are
        resumable: on replica death the proxy's router re-submits here
        with the delivered-token cursor and only the remaining events
        are produced."""
        try:
            body = request.json()
        except Exception:
            return _http_error(400, "body must be JSON")
        if not isinstance(body, dict) or "tokens" not in body:
            return _http_error(400, 'body must be {"tokens": [...]}')
        wants_sse = _wants_stream(request)
        overrides = {k: body[k] for k in _GEN_KEYS if k in body}
        session = body.get("session") or (_resume or {}).get("session")
        try:
            kw = self._gen_kwargs(overrides)
            if wants_sse:
                toks, remaining = self._trim_for_resume(
                    body["tokens"], kw, _resume)
                if remaining <= 0:
                    return self._no_events()
                rng_state = await self._prepare_kv(_resume, toks,
                                                   session)
                stream = self.engine.submit(toks, session_id=session,
                                            rng_state=rng_state, **kw)
                return self._sse_events(stream)
            toks = [int(t) for t in body["tokens"]]
            rng_state = await self._prepare_kv(None, toks, session)
            out = await self.engine.generate(
                toks, session_id=session, rng_state=rng_state, **kw)
        except EngineOverloadedError as e:
            # Retry-After tracks WHAT saturated: a full waiting line
            # drains at admission speed (short), an exhausted KV pool
            # drains at generation speed (longer).  Seconds as a FLOAT:
            # the engine's tier-aware hint can be sub-second — one
            # demotion sweep away — and the old max(1, int(...))
            # rounding turned 0.25s of backoff into a full second of
            # idle client on every retry.
            retry = f"{max(0.05, float(getattr(e, 'retry_after_s', 1.0))):.3f}"
            return _http_error(503, str(e),
                               headers=[("Retry-After", retry)])
        except (TypeError, ValueError) as e:
            return _http_error(400, str(e))
        return {"tokens": out}

    async def _sse_events(self, stream):
        try:
            async for tok in stream:
                yield {"token": int(tok)}
        finally:
            stream.cancel()  # client went away mid-generation: free the slot

    async def _no_events(self):
        """A resumed stream whose cursor already covers the whole
        generation: stream transport, zero remaining events."""
        return
        yield  # pragma: no cover — marks this as a generator function


def _wants_stream(request) -> bool:
    """THE streaming-detection predicate — literally the proxy's own
    (HTTPProxy.wants_stream), so the replica's choice of generator vs
    unary can never drift from the transport the proxy picked."""
    from ray_tpu.serve._private.http_proxy import HTTPProxy
    return HTTPProxy.wants_stream(getattr(request, "query", None) or {},
                                  getattr(request, "headers", None) or {})


def _http_error(status: int, message: str, headers=None) -> Dict:
    """Structured response the HTTP proxy unwraps (same contract as the
    ASGI ingress path)."""
    return {"__http__": True, "status": status,
            "content_type": "application/json",
            "headers": list(headers or []),
            "body": json.dumps({"error": message}).encode()}


def llm_deployment(model_loader, *, name: str = "llm",
                   num_replicas: int = 1,
                   engine_config: Optional[Dict] = None,
                   default_generation: Optional[Dict] = None,
                   route_prefix: Optional[str] = None,
                   max_concurrent_queries: int = 256,
                   ray_actor_options: Optional[Dict] = None):
    """Build a ready-to-deploy LLMServer Deployment.

        handle = llm_deployment(loader, engine_config={"num_slots": 8}
                                ).deploy()
        tokens = handle.generate.remote([1, 2, 3]).result()
        for tok in handle.options("stream").stream([1, 2, 3]):
            ...
    """
    from ray_tpu.serve.api import deployment
    dep = deployment(
        LLMServer, name=name, num_replicas=num_replicas,
        max_concurrent_queries=max_concurrent_queries,
        ray_actor_options=ray_actor_options, route_prefix=route_prefix)
    return dep.options(init_args=(model_loader, engine_config,
                                  default_generation))
