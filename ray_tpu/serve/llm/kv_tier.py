"""Cold-tier backing for the KV memory hierarchy (T1 arena, T2 store).

The engine's three tiers:

  T0  decode pool — pages live on device, addressed through block
      tables (paging.py owns the accounting);
  T1  HostKVArena — one /dev/shm-backed mmap per engine, fixed-size
      page slots over a byte budget (the same arena-mmap pattern the
      transfer plane's same-host path uses).  Fast demote/promote, dies
      with the process;
  T2  KVPageStore — a host-shared spill directory of content-addressed
      page files plus session manifests.  Survives replica death; any
      replica on the host can import from it — which is exactly what
      makes a durable session resurrect anywhere.

Integrity discipline is kv_transfer's, applied at rest: every page
travels as one frame (K bytes + V bytes, `page_frame`), every frame
carries a CRC32 checked before anything touches the device, and a store
write is temp-file + rename so a reader can never observe a torn page.
A failed read is a MISS (the caller re-prefills), never a corrupt
import — the all-or-nothing bar migration set applies to tiers too.

Single-owner discipline: arena and store methods are called from the
engine's worker thread (the store's files are additionally shared
across processes, which the atomic-rename write makes safe).
"""

from __future__ import annotations

import json
import logging
import mmap
import os
import struct
import tempfile
import time
import uuid
import zlib
from typing import Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else None
# Store page/manifest file header: magic, CRC32 of the body, body length.
_HDR = struct.Struct("<4sII")
_MAGIC = b"rtkv"


def page_frame(k_page: np.ndarray, v_page: np.ndarray) -> bytes:
    """One page's wire/at-rest frame: K bytes then V bytes, contiguous.
    The SAME framing kv_transfer puts on migration frames, so a tier
    and a peer replica are interchangeable sources for an import."""
    return k_page.tobytes() + v_page.tobytes()


def frame_crc(frame: bytes) -> int:
    return zlib.crc32(frame)


def split_frame(frame: bytes, k_nbytes: int, kshape, vshape,
                dtype) -> tuple:
    """Inverse of page_frame: (k, v) arrays of the given shapes."""
    k = np.frombuffer(frame[:k_nbytes], dtype).reshape(kshape)
    v = np.frombuffer(frame[k_nbytes:], dtype).reshape(vshape)
    return k, v


class HostKVArena:
    """Fixed-slot host arena for demoted KV pages (tier T1).

    One mmap of capacity * page_nbytes bytes, /dev/shm-backed when
    available (anonymous otherwise — same lifetime, no name).  Slots
    are recycled LIFO; the caller (the radix trie's payload) records
    which slot holds which page plus its CRC — the arena itself is
    deliberately dumb storage."""

    def __init__(self, page_nbytes: int, budget_bytes: int,
                 name: str = "default"):
        if page_nbytes < 1:
            raise ValueError("page_nbytes must be >= 1")
        self.page_nbytes = int(page_nbytes)
        self.capacity = max(1, int(budget_bytes) // self.page_nbytes)
        size = self.capacity * self.page_nbytes
        self._path: Optional[str] = None
        if _SHM_DIR is not None:
            self._path = os.path.join(
                _SHM_DIR, f"rt_kvarena_{name}_{uuid.uuid4().hex[:8]}")
            try:
                with open(self._path, "wb") as f:
                    f.truncate(size)
                self._file = open(self._path, "r+b")
                self._mm = mmap.mmap(self._file.fileno(), size)
            except OSError:
                self._path = None
        if self._path is None:
            self._file = None
            self._mm = mmap.mmap(-1, size)
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self._closed = False

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def used_slots(self) -> int:
        return self.capacity - len(self._free)

    def put(self, frame: bytes) -> Optional[int]:
        """Stage one page frame; returns its slot or None when the
        budget is spent (the sweeper then demotes to the store tier
        instead — the arena is a cache over T2, never a hard wall)."""
        if self._closed or not self._free \
                or len(frame) != self.page_nbytes:
            return None
        slot = self._free.pop()
        base = slot * self.page_nbytes
        self._mm[base:base + len(frame)] = frame
        return slot

    def get(self, slot: int) -> Optional[bytes]:
        if self._closed or not 0 <= slot < self.capacity:
            return None
        base = slot * self.page_nbytes
        return bytes(self._mm[base:base + self.page_nbytes])

    def free(self, slot: int) -> None:
        if not self._closed and 0 <= slot < self.capacity \
                and slot not in self._free:
            self._free.append(slot)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._mm.close()
        except (OSError, ValueError):
            pass
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
        if self._path:
            try:
                os.unlink(self._path)
            except OSError:
                pass


def default_store_dir() -> str:
    """The host-shared spill directory every engine on this host
    agrees on (uid-scoped, the tempdir convention): config's
    serve_kv_store_dir when set, else <tempdir>/rt_kv_store-<uid>."""
    from ray_tpu._private.config import GLOBAL_CONFIG as _cfg
    configured = getattr(_cfg, "serve_kv_store_dir", "") or ""
    if configured:
        return configured
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"rt_kv_store-{uid}")


def _atomic_write(path: str, payload: bytes) -> bool:
    """temp + rename so a concurrent reader (another replica pulling a
    resurrecting session) can never observe a torn file."""
    tmp = f"{path}.tmp.{uuid.uuid4().hex[:8]}"
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
        return True
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _checked_read(path: str) -> Optional[bytes]:
    """Read one header-framed file; any miss — absent, torn, CRC
    mismatch — is None, and a corrupt file is unlinked so it cannot
    keep failing future reads."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return None
    if len(data) < _HDR.size:
        return None
    magic, crc, n = _HDR.unpack_from(data)
    body = data[_HDR.size:]
    if magic != _MAGIC or len(body) != n or zlib.crc32(body) != crc:
        logger.warning("kv store entry %s failed integrity check; "
                       "dropping it", os.path.basename(path))
        try:
            os.unlink(path)
        except OSError:
            pass
        return None
    return body


class KVPageStore:
    """Durable page + session-manifest store (tier T2).

    Layout under `root`:
      pages/<fp>.kv        one page frame, content-addressed by the
                           chained prefix fingerprint of the page's
                           full prefix (two replicas that never spoke
                           agree on the key — paging.prefix_fingerprints)
      sessions/<id>.json   session manifest: token history, sampler RNG
                           state, page fingerprint chain, timestamp

    Every file is CRC-framed and atomically replaced; reads validate
    before returning.  sweep() ages both kinds out by mtime."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or default_store_dir()
        self._pages = os.path.join(self.root, "pages")
        self._sessions = os.path.join(self.root, "sessions")
        for d in (self._pages, self._sessions):
            os.makedirs(d, exist_ok=True)

    # -- pages ---------------------------------------------------------

    def _page_path(self, fp: str) -> str:
        return os.path.join(self._pages, f"{fp}.kv")

    def put_page(self, fp: str, frame: bytes) -> bool:
        path = self._page_path(fp)
        if os.path.exists(path):
            # Content-addressed: an existing entry is the same bytes
            # (deterministic prefill), so rewriting buys nothing.
            return True
        hdr = _HDR.pack(_MAGIC, zlib.crc32(frame), len(frame))
        return _atomic_write(path, hdr + frame)

    def get_page(self, fp: str) -> Optional[bytes]:
        return _checked_read(self._page_path(fp))

    def has_page(self, fp: str) -> bool:
        return os.path.exists(self._page_path(fp))

    # -- session manifests ---------------------------------------------

    def _session_path(self, session_id: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in str(session_id))[:128]
        return os.path.join(self._sessions, f"{safe}.json")

    def put_session(self, session_id: str, manifest: Dict) -> bool:
        body = json.dumps(manifest).encode()
        hdr = _HDR.pack(_MAGIC, zlib.crc32(body), len(body))
        return _atomic_write(self._session_path(session_id), hdr + body)

    def get_session(self, session_id: str) -> Optional[Dict]:
        body = _checked_read(self._session_path(session_id))
        if body is None:
            return None
        try:
            return json.loads(body)
        except ValueError:
            return None

    # -- hygiene -------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        try:
            return {"pages": len(os.listdir(self._pages)),
                    "sessions": len(os.listdir(self._sessions))}
        except OSError:
            return {"pages": 0, "sessions": 0}

    def sweep(self, ttl_s: float) -> int:
        """Drop entries untouched for ttl_s (mtime); returns how many.
        Both sweeping engines racing on one shared directory is fine —
        unlink of an already-gone file is a no-op."""
        cutoff = time.time() - ttl_s
        dropped = 0
        for d in (self._pages, self._sessions):
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                path = os.path.join(d, name)
                try:
                    if os.path.getmtime(path) < cutoff:
                        os.unlink(path)
                        dropped += 1
                except OSError:
                    pass
        return dropped
