"""@serve.batch: transparent request batching inside a replica.

Reference: python/ray/serve/batching.py — concurrent calls to the wrapped
async method are buffered; when max_batch_size accumulate or
batch_wait_timeout_s elapses, the underlying function runs once on the
list of requests and each caller gets its element of the list result.
On TPU replicas this is the lever that turns single queries into
MXU-shaped batched forward passes.
"""

from __future__ import annotations

import asyncio
import functools
import weakref
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._pending: List[tuple] = []   # (arg, future)
        self._flusher: Optional[asyncio.TimerHandle] = None
        # Captured at submit() time: _flush may run from a timer
        # callback, where asyncio.get_event_loop() is deprecated (and
        # wrong if the instance migrated loops between batches).
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    async def submit(self, instance, arg) -> Any:
        loop = asyncio.get_running_loop()
        self._loop = loop
        fut = loop.create_future()
        self._pending.append((arg, fut))
        if len(self._pending) >= self._max:
            self._flush(instance)
        elif self._flusher is None:
            self._flusher = loop.call_later(
                self._timeout, self._flush, instance)
        return await fut

    def _flush(self, instance):
        if self._flusher is not None:
            self._flusher.cancel()
            self._flusher = None
        batch, self._pending = self._pending, []
        if not batch:
            return
        args = [a for a, _ in batch]
        futs = [f for _, f in batch]
        loop = self._loop

        async def _run():
            try:
                if instance is not None:
                    results = await self._fn(instance, args)
                else:
                    results = await self._fn(args)
                if not isinstance(results, (list, tuple)) \
                        or len(results) != len(args):
                    raise ValueError(
                        "@serve.batch function must return a list with "
                        f"one result per input ({len(args)} expected)")
                for f, r in zip(futs, results):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:
                for f in futs:
                    if not f.done():
                        f.set_exception(e)

        loop.create_task(_run())


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator for an async method taking a LIST of requests."""

    def _decorate(fn):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async function")
        # Registry: id(instance) -> (weakref-to-instance, _BatchQueue).
        # The weakref serves two jobs: its death callback evicts the
        # entry (a plain id-keyed dict outlives every replica restart —
        # a leak), and the `wr() is instance` check catches id() reuse
        # (a NEW object allocated at a dead object's address must not
        # inherit the dead object's queue).
        queues: dict = {}

        def _queue_for(instance):
            key = id(instance)
            entry = queues.get(key)
            if entry is not None:
                wr, q = entry
                if wr is None or wr() is instance:
                    return q
                del queues[key]  # id reused by a different object
            q = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
            if instance is None:
                wr = None  # free function: one immortal queue
            else:
                def _on_death(ref, _key=key):
                    # GC can defer this callback (reference cycles)
                    # until AFTER the key was reused by a successor
                    # instance: only evict the entry if it is still
                    # OURS.
                    cur = queues.get(_key)
                    if cur is not None and cur[0] is ref:
                        queues.pop(_key, None)
                try:
                    wr = weakref.ref(instance, _on_death)
                except TypeError:
                    # Non-weakrefable instance: pin it (a strong-ref
                    # closure) so its id can never be reused — the old
                    # leak, but only for exotic classes.
                    wr = (lambda obj: (lambda: obj))(instance)
            queues[key] = (wr, q)
            return q

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:       # bound method: (self, item)
                instance, item = args
            elif len(args) == 1:     # free function: (item,)
                instance, item = None, args[0]
            else:
                raise TypeError("@serve.batch methods take one argument")
            return await _queue_for(instance).submit(instance, item)

        wrapper._rt_serve_batch = True
        wrapper._rt_batch_queues = queues  # introspection for tests
        return wrapper

    if _fn is not None:
        return _decorate(_fn)
    return _decorate
