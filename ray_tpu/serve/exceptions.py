"""Serve-layer structured errors and the resumable-stream marker.

Robustness contract (reference: serve's replica fault tolerance,
PAPER.md L10): a replica death mid-request must surface as one of a
small set of STRUCTURED outcomes — a transparent retry/failover, a
:class:`StreamInterrupted` carrying a resume cursor, or a
:class:`TenantThrottled` shed — never as a raw ActorDiedError leaking
to an HTTP client and never as a silent hang.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class StreamInterrupted(RuntimeError):
    """A streaming request died mid-flight and could not (or was not
    allowed to) fail over to another replica.

    Carries a RESUME CURSOR: the consumer knows exactly how many items
    were delivered before the interruption, so a client that holds the
    original request can re-submit with the delivered prefix appended
    (for resumable deployments this is what the router does
    automatically when failover is enabled).  Delivered items are never
    re-sent — the stream either continues past the cursor or stops
    here, so the consumer's view is always a prefix of the true
    stream."""

    def __init__(self, message: str, *, deployment: str = "",
                 method: str = "", delivered: int = 0,
                 resumable: bool = False,
                 cause: Optional[str] = None,
                 kv_origin: Optional[Dict] = None,
                 digest: Optional[list] = None):
        super().__init__(message)
        self.deployment = deployment
        self.method = method
        self.delivered = delivered
        self.resumable = resumable
        self.cause = cause
        # KV-affinity cursor extras (both optional): where the dead
        # replica's committed pages can still be pulled from, and the
        # request's prefix fingerprints — a client resuming through a
        # DIFFERENT proxy replays these (x-rt-resume / x-rt-affinity)
        # so the resumed stream lands with affinity and can migrate the
        # pages instead of re-prefilling.
        self.kv_origin = kv_origin
        self.digest = digest

    @property
    def resume_cursor(self) -> Dict[str, Any]:
        """Everything a holder of the original (method, args, kwargs)
        needs to resume: where the stream stopped, whether the
        deployment supports server-side resumption, and (when known)
        the KV affinity extras."""
        cur = {"deployment": self.deployment, "method": self.method,
               "delivered": self.delivered, "resumable": self.resumable}
        if self.kv_origin:
            cur["kv_origin"] = self.kv_origin
        if self.digest:
            cur["digest"] = list(self.digest)
        return cur

    def __reduce__(self):
        return (_rebuild_stream_interrupted,
                (self.args[0] if self.args else "", self.deployment,
                 self.method, self.delivered, self.resumable, self.cause,
                 self.kv_origin, self.digest))


def _rebuild_stream_interrupted(msg, deployment, method, delivered,
                                resumable, cause, kv_origin=None,
                                digest=None):
    return StreamInterrupted(msg, deployment=deployment, method=method,
                             delivered=delivered, resumable=resumable,
                             cause=cause, kv_origin=kv_origin,
                             digest=digest)


class TenantThrottled(RuntimeError):
    """Per-tenant admission refused the request (token bucket empty or
    the tenant's waiting line is full).  Overload becomes an immediate,
    retryable signal — HTTP 429 + Retry-After at the proxy — instead of
    queue inflation that bleeds into every other tenant's p99.

    `reason` is "rate_limited" (bucket empty; retry after the bucket
    refills one token) or "queue_full" (too many queued waiters for
    this tenant; retry after the line drains)."""

    def __init__(self, message: str, *, tenant: str = "default",
                 reason: str = "rate_limited",
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.tenant = tenant
        self.reason = reason
        self.retry_after_s = retry_after_s

    def __reduce__(self):
        return (_rebuild_tenant_throttled,
                (self.args[0] if self.args else "", self.tenant,
                 self.reason, self.retry_after_s))


def _rebuild_tenant_throttled(msg, tenant, reason, retry_after_s):
    return TenantThrottled(msg, tenant=tenant, reason=reason,
                           retry_after_s=retry_after_s)


def resumable(fn):
    """Mark a streaming deployment method as RESUMABLE: it accepts a
    ``_resume`` keyword ({"delivered": n, "items": [...]} — the items
    already handed to the consumer) and yields only what comes AFTER
    that prefix.  The router re-submits interrupted streams of marked
    methods on a healthy replica instead of raising StreamInterrupted.

        class LLM:
            @serve.resumable
            async def stream(self, tokens, _resume=None, **kw): ...
    """
    fn.__serve_resumable__ = True
    return fn
