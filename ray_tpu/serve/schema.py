"""Declarative Serve config: schema, build, and deploy.

Reference: python/ray/serve/schema.py:202 (ServeApplicationSchema — the
YAML the `serve build` / `serve deploy` CLI round-trips) and
serve/scripts.py.  The config describes deployments by import path plus
option overrides; applying it is idempotent and version-preserving —
deployments whose code and options are unchanged keep their
content-derived version, so the controller's reconciliation leaves their
replicas untouched (zero-downtime re-apply)."""

from __future__ import annotations

import importlib
from typing import Dict, List, Optional

_DEPLOYMENT_KEYS = {
    "name": str,
    "import_path": str,
    "num_replicas": int,
    "max_concurrent_queries": int,
    "user_config": dict,
    "ray_actor_options": dict,
    "route_prefix": (str, type(None)),
    "version": str,
    "autoscaling_config": dict,
    "graceful_shutdown_timeout_s": (int, float),
    "health_check_period_s": (int, float),
    "health_check_timeout_s": (int, float),
}


class ServeConfigError(ValueError):
    pass


def validate_config(config: Dict) -> List[Dict]:
    """Validate a declarative config; returns the deployment spec list.

    Accepted top-level shapes: {"applications": [...]} (reference
    multi-app schema) or {"deployments": [...]} (single-app schema)."""
    if not isinstance(config, dict):
        raise ServeConfigError(
            f"config must be a mapping, got {type(config).__name__}")
    specs = config.get("applications", config.get("deployments"))
    if not isinstance(specs, list) or not specs:
        raise ServeConfigError(
            "config needs a non-empty 'applications' (or 'deployments') "
            "list")
    for i, spec in enumerate(specs):
        if not isinstance(spec, dict):
            raise ServeConfigError(f"applications[{i}] must be a mapping")
        if not spec.get("import_path"):
            raise ServeConfigError(
                f"applications[{i}] is missing required 'import_path' "
                "(format: module.submodule:deployment_attr)")
        if ":" not in spec["import_path"]:
            raise ServeConfigError(
                f"applications[{i}].import_path "
                f"{spec['import_path']!r} must be 'module:attribute'")
        for key, value in spec.items():
            expected = _DEPLOYMENT_KEYS.get(key)
            if expected is None:
                raise ServeConfigError(
                    f"applications[{i}] has unknown option {key!r}; "
                    f"valid: {sorted(_DEPLOYMENT_KEYS)}")
            if not isinstance(value, expected):
                raise ServeConfigError(
                    f"applications[{i}].{key} must be "
                    f"{getattr(expected, '__name__', expected)}, got "
                    f"{type(value).__name__}")
    return specs


def _resolve(import_path: str):
    from ray_tpu.serve.api import Deployment
    mod_name, _, attr = import_path.partition(":")
    target = getattr(importlib.import_module(mod_name), attr, None)
    if not isinstance(target, Deployment):
        raise ServeConfigError(
            f"{import_path} does not resolve to a serve Deployment")
    return target


def apply_config(config: Dict) -> List[str]:
    """Validate + deploy every application; returns deployed names.
    Unchanged deployments keep their content-derived version, so the
    re-apply is a controller no-op for them."""
    specs = validate_config(config)
    deployed = []
    for spec in specs:
        target = _resolve(spec["import_path"])
        opts = {k: v for k, v in spec.items() if k != "import_path"}
        if opts:
            target = target.options(**opts)
        target.deploy()
        deployed.append(target.name)
    return deployed


def build_config(import_paths: List[str]) -> Dict:
    """`serve build`: resolve deployments and emit the declarative
    config capturing their CURRENT options (reference: serve build
    emitting ServeApplicationSchema YAML)."""
    apps = []
    for path in import_paths:
        d = _resolve(path)
        spec: Dict = {"name": d.name, "import_path": path}
        cfg = d.config.to_dict()
        for key in ("num_replicas", "max_concurrent_queries",
                    "graceful_shutdown_timeout_s",
                    "health_check_period_s", "health_check_timeout_s"):
            if key in cfg:
                spec[key] = cfg[key]
        if cfg.get("user_config"):
            spec["user_config"] = cfg["user_config"]
        if cfg.get("autoscaling_config"):
            spec["autoscaling_config"] = dict(cfg["autoscaling_config"])
        if d.route_prefix is not None:
            spec["route_prefix"] = d.route_prefix
        if getattr(d, "ray_actor_options", None):
            spec["ray_actor_options"] = dict(d.ray_actor_options)
        apps.append(spec)
    return {"applications": apps}


def load_config_file(path: str) -> Dict:
    import yaml
    with open(path) as f:
        return yaml.safe_load(f)


def dump_config_file(config: Dict, path: Optional[str] = None) -> str:
    import yaml
    text = yaml.safe_dump(config, sort_keys=False)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text
