"""Serve: online model serving on ray_tpu.

Deployments are reconciled by a controller actor toward their declared
target (replicas, version, autoscaling); queries route through
max_concurrent_queries-aware routers; HTTP ingress via an aiohttp proxy
actor.  Reference: python/ray/serve (SURVEY.md §2.3, §3.5).
"""

from ray_tpu.serve.api import (  # noqa: F401
    Deployment,
    build,
    delete,
    deployment,
    get_deployment,
    get_deployment_handle,
    get_proxy_address,
    get_proxy_addresses,
    ingress,
    list_deployments,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.context import (  # noqa: F401
    ReplicaContext,
    get_replica_context,
)
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.config import (  # noqa: F401
    AutoscalingConfig,
    DeploymentConfig,
    HTTPOptions,
)
from ray_tpu.serve.handle import (  # noqa: F401
    DeploymentHandle,
    RayServeHandle,
    ServeResponseStream,
)
from ray_tpu.serve.exceptions import (  # noqa: F401
    StreamInterrupted,
    TenantThrottled,
    resumable,
)
from ray_tpu.serve._private.replica import Request  # noqa: F401

__all__ = [
    "AutoscalingConfig", "Deployment", "DeploymentConfig",
    "DeploymentHandle", "HTTPOptions", "RayServeHandle", "ReplicaContext",
    "Request", "ServeResponseStream", "StreamInterrupted",
    "TenantThrottled",
    "batch", "build", "delete", "deployment", "get_deployment",
    "get_deployment_handle", "get_proxy_address", "get_proxy_addresses",
    "get_replica_context", "ingress", "list_deployments", "resumable",
    "run", "shutdown", "start", "status",
]

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("serve")
del _rlu
