"""Serve configuration types.

Reference: python/ray/serve/config.py (DeploymentConfig, AutoscalingConfig,
HTTPOptions) — target state declared per deployment; the controller
reconciles actual replicas toward it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Reference: serve/config.py AutoscalingConfig + the policy inputs in
    serve/_private/autoscaling_policy.py.

    Flap suppression for noisy gauges (chaos, bursty traffic): the
    scaler acts on an EWMA of the cluster-wide load signal
    (`load_ewma_alpha`; 1.0 = raw samples) and, after any decision,
    holds fire for `decision_cooldown_s` — so replica counts change at
    most once per cooldown window however hard the gauges shake."""
    min_replicas: int = 1
    max_replicas: int = 4
    target_num_ongoing_requests_per_replica: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0
    metrics_interval_s: float = 1.0
    smoothing_factor: float = 1.0
    decision_cooldown_s: float = 0.0
    load_ewma_alpha: float = 1.0
    # Cluster-autopilot declaration (_private/arbiter.py): when
    # slo_ttft_p99_s is set, the controller registers this deployment
    # with the GCS broker, reports its p99 TTFT attainment every tick,
    # and caps scale-ups at the broker's granted budget.  A sustained
    # breach lets the broker reclaim capacity from lower-priority
    # workloads (elastic train gangs shrink, data leases revoke) to
    # honor the SLO.
    slo_ttft_p99_s: Optional[float] = None
    priority: int = 100


@dataclass
class DeploymentConfig:
    """Target state for one deployment (reference: serve/config.py:71
    DeploymentConfig protobuf-backed model)."""
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    user_config: Any = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    graceful_shutdown_timeout_s: float = 10.0
    health_check_period_s: float = 5.0
    health_check_timeout_s: float = 30.0
    # Scale-down drains: a surplus replica first stops admitting (left
    # out of the router broadcast) and finishes its in-flight requests
    # — including long-lived streams — before it is retired; only past
    # this bound is it stopped with work still in flight.
    drain_timeout_s: float = 60.0

    def to_dict(self) -> Dict:
        d = dict(self.__dict__)
        if self.autoscaling_config is not None:
            d["autoscaling_config"] = dict(
                self.autoscaling_config.__dict__)
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> "DeploymentConfig":
        d = dict(d)
        ac = d.get("autoscaling_config")
        if isinstance(ac, dict):
            d["autoscaling_config"] = AutoscalingConfig(**ac)
        return cls(**d)


@dataclass
class ReplicaConfig:
    """How to construct one replica: the serialized deployment body +
    actor options (reference: serve/config.py ReplicaConfig which carries
    the pickled deployment_def)."""
    deployment_def: bytes = b""          # cloudpickle of class or function
    init_args: tuple = ()
    init_kwargs: Dict = field(default_factory=dict)
    ray_actor_options: Dict = field(default_factory=dict)


@dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000
