"""User-facing exception types.

Reference: python/ray/exceptions.py — RayError hierarchy (RayTaskError
wrapping the remote exception + traceback, RayActorError, GetTimeoutError,
ObjectLostError, WorkerCrashedError).
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ray_tpu.get().

    Carries the remote traceback text like the reference's RayTaskError
    (python/ray/exceptions.py:46)."""

    def __init__(self, cause_repr: str, traceback_str: str = "",
                 cause: BaseException | None = None):
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"{cause_repr}\n{traceback_str}")

    def as_instanceof_cause(self):
        if isinstance(self.cause, Exception):
            return _wrap_cause(self.cause, self.traceback_str)
        return self


def _wrap_cause(cause: Exception, tb: str):
    """Return an exception that is an instance of the original cause's type
    AND of TaskError, so `except ValueError` works on the caller."""
    cause_cls = type(cause)
    if isinstance(cause, TaskError):
        return cause
    try:
        derived = type("TaskError_" + cause_cls.__name__, (TaskError, cause_cls), {
            # Must swallow positional args: unpickling an exception calls
            # cls(*self.args), and these wrappers carry a message arg.
            "__init__": lambda self, *a: None,
        })
        exc = derived()
        # Carry the cause's structured attributes (e.g.
        # CollectiveGroupError.group) so callers that dispatch on them
        # see the same shape whether the error was raised locally or
        # re-raised at get().
        exc.__dict__.update(cause.__dict__)
        exc.cause = cause
        exc.cause_repr = repr(cause)
        exc.traceback_str = tb
        exc.args = (f"{cause!r}\nRemote traceback:\n{tb}",)
        return exc
    except TypeError:
        return TaskError(repr(cause), tb, cause)


class ActorError(RayTpuError):
    """The actor died before or during this method call (reference:
    RayActorError)."""

    def __init__(self, actor_id=None, cause: str = "actor died"):
        self.actor_id = actor_id
        super().__init__(f"Actor {actor_id} unavailable: {cause}")


class ActorDiedError(ActorError):
    pass


class ActorUnavailableError(ActorError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    """The task was cancelled with ray_tpu.cancel (reference:
    ray.exceptions.TaskCancelledError)."""


class ObjectLostError(RayTpuError):
    def __init__(self, object_id_hex: str, cause: str = ""):
        super().__init__(f"Object {object_id_hex} lost: {cause}")


class WorkerCrashedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


# Backwards-compatible aliases matching reference names.
RayError = RayTpuError
RayTaskError = TaskError
RayActorError = ActorError
