"""ActorPool: load-balance tasks over a fixed set of actors.

Reference: python/ray/util/actor_pool.py — same verbs (submit/map/
map_unordered/get_next/has_next), re-implemented over this runtime's
wait primitive.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list = []

    def submit(self, fn: Callable, value: Any):
        """fn(actor, value) -> ObjectRef; queued if no actor is idle."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in submission order."""
        if self._next_return_index not in self._index_to_future:
            raise StopIteration("no pending results")
        ref = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        # Return the actor BEFORE get: a raising task must not leak its
        # actor out of the pool (reference ActorPool does the same).
        self._return_actor(ref)
        return ray_tpu.get(ref, timeout=timeout)

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Whichever pending result finishes first."""
        if not self._index_to_future:
            raise StopIteration("no pending results")
        refs = list(self._index_to_future.values())
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result ready in time")
        ref = ready[0]
        for idx, r in list(self._index_to_future.items()):
            if r == ref:
                del self._index_to_future[idx]
                break
        self._return_actor(ref)
        return ray_tpu.get(ref, timeout=timeout)

    def _return_actor(self, ref):
        actor = self._future_to_actor.pop(ref, None)
        if actor is None:
            return
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            new_ref = fn(actor, value)
            self._future_to_actor[new_ref] = actor
            self._index_to_future[self._next_task_index] = new_ref
            self._next_task_index += 1
        else:
            self._idle.append(actor)

    def map(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()
