"""Utility APIs (reference: python/ray/util/__init__.py — ActorPool,
inspect_serializability, metrics, placement groups, queue, collective)."""

from ray_tpu.util.actor_pool import ActorPool  # noqa: F401
from ray_tpu.util.check_serialize import inspect_serializability  # noqa: F401

__all__ = ["ActorPool", "inspect_serializability"]
