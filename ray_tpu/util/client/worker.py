"""Client-side API: drive a cluster from outside it.

Reference: python/ray/util/client/worker.py — a thin synchronous facade
whose every verb becomes an RPC to the in-cluster proxy; ObjectRefs and
ActorHandles exist client-side only as stubs.  Usage:

    from ray_tpu.util import client
    api = client.connect("head-host:10001")
    ref = api.put(42)
    api.get(ref)                      # -> 42
    f = api.remote(lambda x: x + 1)
    api.get(f.remote(1))              # -> 2
    api.disconnect()
"""

from __future__ import annotations

import asyncio
import threading
from typing import Dict, List

from ray_tpu._private import protocol
from ray_tpu.util.client.common import dumps_with, loads_with


class ClientObjectRef:
    __slots__ = ("id", "_api", "__weakref__")

    def __init__(self, ref_id: str, api: "ClientAPI"):
        self.id = ref_id
        self._api = api
        api._live_refs[ref_id] = api._live_refs.get(ref_id, 0) + 1

    def hex(self) -> str:
        return self.id

    def __del__(self):
        try:
            self._api._release(self.id)
        except Exception:
            pass

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ClientObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ClientObjectRef({self.id})"


class ClientActorMethod:
    def __init__(self, handle: "ClientActorHandle", name: str):
        self._handle = handle
        self._name = name
        self._opts: Dict = {}

    def options(self, **opts) -> "ClientActorMethod":
        m = ClientActorMethod(self._handle, self._name)
        m._opts = opts
        return m

    def remote(self, *args, **kwargs):
        return self._handle._api._actor_call(
            self._handle, self._name, args, kwargs, self._opts)


class ClientActorHandle:
    def __init__(self, actor_id: str, class_name: str, method_meta: Dict,
                 api: "ClientAPI"):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_meta = method_meta or {}
        self._api = api

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self, name)

    def __repr__(self):
        return f"ClientActorHandle({self._class_name}, {self._actor_id[:16]})"


class ClientRemoteFunction:
    def __init__(self, fn, api: "ClientAPI", opts: Dict | None = None):
        self._fn = fn
        self._api = api
        self._opts = opts or {}

    def options(self, **opts) -> "ClientRemoteFunction":
        return ClientRemoteFunction(self._fn, self._api,
                                    {**self._opts, **opts})

    def remote(self, *args, **kwargs):
        return self._api._task(self._fn, args, kwargs, self._opts)


class ClientRemoteClass:
    def __init__(self, cls, api: "ClientAPI", opts: Dict | None = None):
        self._cls = cls
        self._api = api
        self._opts = opts or {}

    def options(self, **opts) -> "ClientRemoteClass":
        return ClientRemoteClass(self._cls, self._api,
                                 {**self._opts, **opts})

    def remote(self, *args, **kwargs):
        return self._api._create_actor(self._cls, args, kwargs,
                                       self._opts)


class ClientAPI:
    """The connected client: mirrors the ray_tpu module verbs."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._live_refs: Dict[str, int] = {}
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever,
                                        name="rt-client-io", daemon=True)
        self._thread.start()
        self._conn: protocol.Connection = self._call_async(
            protocol.Connection.connect(host, port, handler=self._on_push,
                                        name="client"), timeout)
        self._req("hello")

    # ------------------------------------------------------- plumbing
    def _call_async(self, coro, timeout=None):
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result(timeout)

    def _req(self, method: str, body=None, timeout: float | None = 300.0):
        return self._call_async(
            self._conn.request(method, body, timeout=timeout),
            None if timeout is None else timeout + 5)

    async def _on_push(self, conn, method, body):
        return None

    def _persist(self, obj):
        """Client->server: stubs travel as persistent ids."""
        if isinstance(obj, ClientObjectRef):
            return ("ref", obj.id)
        if isinstance(obj, ClientActorHandle):
            return ("actor", obj._actor_id)
        from ray_tpu._private.object_ref import ObjectRefGenerator
        if isinstance(obj, ObjectRefGenerator):
            # A generator fetched through this client wraps stub refs;
            # send the ids, the server rebinds them to its real refs.
            return ("refgen", tuple(r.hex() for r in obj))
        return None

    def _load(self, pid):
        """Server->client: real refs/handles arrive as stub ids."""
        if pid[0] == "ref":
            return ClientObjectRef(pid[1], self)
        if pid[0] == "actor":
            return ClientActorHandle(pid[1], pid[2], {}, self)
        if pid[0] == "refgen":
            # num_returns="dynamic" parity: the generator arrives as
            # its sub-object ids; rebuild it over client stubs so
            # iteration/len/indexing behave like the in-process API.
            from ray_tpu._private.object_ref import ObjectRefGenerator
            return ObjectRefGenerator(
                [ClientObjectRef(h, self) for h in pid[1]])
        raise ValueError(f"bad persistent id {pid!r}")

    def _release(self, ref_id: str):
        n = self._live_refs.get(ref_id, 0) - 1
        if n > 0:
            self._live_refs[ref_id] = n
            return
        self._live_refs.pop(ref_id, None)
        if self._conn is not None and not self._conn.closed:
            asyncio.run_coroutine_threadsafe(
                self._conn.push("release", {"ids": [ref_id]}), self._loop)

    # ------------------------------------------------------- public API
    def put(self, value) -> ClientObjectRef:
        blob = dumps_with(value, self._persist)
        return ClientObjectRef(self._req("put", {"blob": blob}), self)

    def get(self, refs, *, timeout: float | None = None):
        single = isinstance(refs, ClientObjectRef)
        if single:
            refs = [refs]
        # timeout=None must block exactly as long as the server-side get
        # does — no hidden RPC deadline.
        blobs = self._req("get", {"ids": [r.id for r in refs],
                                  "timeout": timeout},
                          timeout=None if timeout is None
                          else timeout + 30)
        values = [loads_with(b, self._load) for b in blobs]
        return values[0] if single else values

    def wait(self, refs, *, num_returns: int = 1,
             timeout: float | None = None, fetch_local: bool = True):
        by_id = {r.id: r for r in refs}
        ready, pending = self._req(
            "wait", {"ids": [r.id for r in refs],
                     "num_returns": num_returns, "timeout": timeout,
                     "fetch_local": fetch_local},
            timeout=None if timeout is None else timeout + 30)
        return ([by_id[h] for h in ready], [by_id[h] for h in pending])

    def remote(self, target=None, **opts):
        """Decorator/wrapper parity with ray_tpu.remote."""
        if target is None:
            return lambda t: self.remote(t, **opts)
        if isinstance(target, type):
            return ClientRemoteClass(target, self, opts)
        return ClientRemoteFunction(target, self, opts)

    def _task(self, fn, args, kwargs, opts) -> ClientObjectRef:
        blob = dumps_with((fn, args, kwargs), self._persist)
        hexes = self._req("task", {"blob": blob, "opts": opts})
        refs = [ClientObjectRef(h, self) for h in hexes]
        return refs[0] if len(refs) == 1 else refs

    def _create_actor(self, cls, args, kwargs, opts) -> ClientActorHandle:
        blob = dumps_with((cls, args, kwargs), self._persist)
        info = self._req("create_actor", {"blob": blob, "opts": opts})
        return ClientActorHandle(info["actor"], info["class_name"],
                                 info["method_meta"], self)

    def _actor_call(self, handle, method, args, kwargs, opts):
        num_returns = opts.get("num_returns", 1)
        if num_returns == "dynamic":
            # Parity with the in-process API (actor.py _invoke): reject
            # client-side rather than shipping a call the server will
            # refuse with a less local error.
            raise ValueError(
                'num_returns="dynamic" is only supported for task '
                "returns, not actor methods")
        blob = dumps_with((args, kwargs), self._persist)
        hexes = self._req("actor_call",
                          {"actor": handle._actor_id, "method": method,
                           "blob": blob, "opts": opts,
                           "num_returns": num_returns})
        refs = [ClientObjectRef(h, self) for h in hexes]
        return refs[0] if len(refs) == 1 else refs

    def get_actor(self, name: str,
                  namespace: str = "default") -> ClientActorHandle:
        info = self._req("get_actor", {"name": name,
                                       "namespace": namespace})
        return ClientActorHandle(info["actor"], info["class_name"],
                                 info["method_meta"], self)

    def kill(self, handle: ClientActorHandle, *, no_restart: bool = True):
        return self._req("kill", {"actor": handle._actor_id,
                                  "no_restart": no_restart})

    def cancel(self, ref: ClientObjectRef, *, force: bool = False):
        return self._req("cancel", {"id": ref.id, "force": force})

    def nodes(self) -> List[Dict]:
        return self._req("cluster_info", {"kind": "nodes"})

    def cluster_resources(self) -> Dict:
        return self._req("cluster_info", {"kind": "cluster_resources"})

    def available_resources(self) -> Dict:
        return self._req("cluster_info",
                         {"kind": "available_resources"})

    def disconnect(self):
        if self._conn is not None:
            try:
                self._call_async(self._conn.close(), 10)
            except Exception:
                pass
            self._conn = None
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.disconnect()
