"""Client server: the in-cluster proxy that out-of-cluster clients drive.

Reference: python/ray/util/client/server — a gRPC server inside the
cluster that executes pickled client calls against a real driver and
hands back ticket stubs.  Here: one RpcServer on the framework protocol
plane; the hosting process is (or becomes) a real driver, and every
client request is executed through the public API in a worker thread so
the RPC loop never blocks on cluster waits.  Divergence from the
reference (noted): all clients share the hosting driver's ownership
context rather than getting an isolated per-client driver — lifetime of
client-created objects is scoped to this server process.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Dict

import ray_tpu
from ray_tpu._private import protocol
from ray_tpu._private.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.actor import ActorHandle
from ray_tpu.util.client.common import dumps_with, loads_with


class ClientServer:
    """Serves out-of-cluster clients over the protocol plane."""

    def __init__(self):
        self._refs: Dict[str, ObjectRef] = {}
        self._actors: Dict[str, ActorHandle] = {}
        self._server: protocol.RpcServer | None = None
        self._lock = threading.Lock()

    # -------------------------------------------------- ref/handle maps
    def _persist(self, obj):
        """Server->client: externalize real refs/handles as stub ids."""
        if isinstance(obj, ObjectRef):
            with self._lock:
                self._refs.setdefault(obj.hex(), obj)
            return ("ref", obj.hex())
        if isinstance(obj, ActorHandle):
            with self._lock:
                self._actors.setdefault(obj._actor_id.hex(), obj)
            return ("actor", obj._actor_id.hex(), obj._class_name)
        if isinstance(obj, ObjectRefGenerator):
            # num_returns="dynamic": the generator's pickle hook would
            # rebuild REAL ObjectRefs client-side (useless stubs there),
            # so externalize it as its sub-ids, tracked like any
            # outbound ref so the client can get() each one.
            with self._lock:
                for r in obj:
                    self._refs.setdefault(r.hex(), r)
            return ("refgen", tuple(r.hex() for r in obj))
        return None

    def _load(self, pid):
        """Client->server: resolve stub ids back to real refs/handles."""
        kind = pid[0]
        if kind == "ref":
            with self._lock:
                ref = self._refs.get(pid[1])
            if ref is None:
                raise KeyError(f"client ref {pid[1]} unknown/released")
            return ref
        if kind == "actor":
            with self._lock:
                handle = self._actors.get(pid[1])
            if handle is None:
                raise KeyError(f"client actor {pid[1]} unknown")
            return handle
        if kind == "refgen":
            return ObjectRefGenerator(
                [self._load(("ref", h)) for h in pid[1]])
        raise ValueError(f"bad persistent id {pid!r}")

    def _track(self, refs):
        refs = refs if isinstance(refs, list) else [refs]
        with self._lock:
            for r in refs:
                self._refs[r.hex()] = r
        return [r.hex() for r in refs]

    # --------------------------------------------------------- handlers
    async def _handle(self, conn, method, body):
        fn = getattr(self, "_rpc_" + method, None)
        if fn is None:
            raise ValueError(f"unknown client rpc {method}")
        return await asyncio.to_thread(fn, body or {})

    def _rpc_hello(self, body):
        return {"ok": True, "protocol": 1}

    def _rpc_put(self, body):
        value = loads_with(body["blob"], self._load)
        ref = ray_tpu.put(value)
        return self._track(ref)[0]

    def _rpc_get(self, body):
        refs = [self._load(("ref", h)) for h in body["ids"]]
        values = ray_tpu.get(refs, timeout=body.get("timeout"))
        if not isinstance(values, list):
            values = [values]
        return [dumps_with(v, self._persist) for v in values]

    def _rpc_wait(self, body):
        refs = [self._load(("ref", h)) for h in body["ids"]]
        ready, pending = ray_tpu.wait(
            refs, num_returns=body.get("num_returns", 1),
            timeout=body.get("timeout"),
            fetch_local=body.get("fetch_local", True))
        return ([r.hex() for r in ready], [r.hex() for r in pending])

    def _rpc_task(self, body):
        payload = loads_with(body["blob"], self._load)
        fn, args, kwargs = payload
        opts = body.get("opts") or {}
        rf = ray_tpu.remote(fn)
        out = rf.options(**opts).remote(*args, **kwargs) if opts \
            else rf.remote(*args, **kwargs)
        return self._track(out)

    def _rpc_create_actor(self, body):
        payload = loads_with(body["blob"], self._load)
        cls, args, kwargs = payload
        opts = body.get("opts") or {}
        ac = ray_tpu.remote(cls)
        handle = ac.options(**opts).remote(*args, **kwargs) if opts \
            else ac.remote(*args, **kwargs)
        with self._lock:
            self._actors[handle._actor_id.hex()] = handle
        return {"actor": handle._actor_id.hex(),
                "class_name": handle._class_name,
                "method_meta": handle._method_meta}

    def _rpc_actor_call(self, body):
        handle = self._load(("actor", body["actor"]))
        payload = loads_with(body["blob"], self._load)
        args, kwargs = payload
        num_returns = body.get("num_returns", 1)
        out = handle._invoke(body["method"], args, kwargs,
                             num_returns, body.get("opts") or {})
        return self._track(out)

    def _rpc_get_actor(self, body):
        handle = ray_tpu.get_actor(body["name"],
                                   body.get("namespace", "default"))
        with self._lock:
            self._actors[handle._actor_id.hex()] = handle
        return {"actor": handle._actor_id.hex(),
                "class_name": handle._class_name,
                "method_meta": handle._method_meta}

    def _rpc_kill(self, body):
        handle = self._load(("actor", body["actor"]))
        ray_tpu.kill(handle, no_restart=body.get("no_restart", True))
        with self._lock:
            self._actors.pop(body["actor"], None)
        return True

    def _rpc_cancel(self, body):
        ref = self._load(("ref", body["id"]))
        return ray_tpu.cancel(ref, force=body.get("force", False))

    def _rpc_release(self, body):
        with self._lock:
            for h in body["ids"]:
                self._refs.pop(h, None)
        return True

    def _rpc_cluster_info(self, body):
        kind = body.get("kind", "nodes")
        if kind == "nodes":
            return ray_tpu.nodes()
        if kind == "cluster_resources":
            return ray_tpu.cluster_resources()
        if kind == "available_resources":
            return ray_tpu.available_resources()
        raise ValueError(kind)

    # ---------------------------------------------------------- running
    async def _start_async(self, host: str, port: int):
        self._server = protocol.RpcServer(self._handle, host=host,
                                          name="client-server")
        await self._server.start(port)
        return self._server.port

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start serving on the framework's background loop; returns the
        bound port."""
        from ray_tpu._private.api import _ensure_loop
        loop = _ensure_loop()
        fut = asyncio.run_coroutine_threadsafe(
            self._start_async(host, port), loop)
        self.port = fut.result(30)
        return self.port

    def stop(self):
        if self._server is not None:
            from ray_tpu._private.api import _ensure_loop
            loop = _ensure_loop()
            asyncio.run_coroutine_threadsafe(
                self._server.stop(), loop).result(10)
            self._server = None


def main(argv=None):
    """`python -m ray_tpu.util.client.server --address HOST:PORT
    [--listen-port N]` — join the cluster as a driver and serve
    clients."""
    import argparse
    import signal
    p = argparse.ArgumentParser()
    p.add_argument("--address", required=True,
                   help="GCS address host:port of the cluster to join")
    p.add_argument("--listen-host", default="0.0.0.0")
    p.add_argument("--listen-port", type=int, default=10001)
    args = p.parse_args(argv)
    ray_tpu.init(address=args.address)
    srv = ClientServer()
    port = srv.start(args.listen_host, args.listen_port)
    print(f"ray_tpu client server listening on "
          f"{args.listen_host}:{port}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    srv.stop()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
