"""Shared pickling machinery for the out-of-cluster client.

Reference: python/ray/util/client (ray://) ships a pickled IR of calls to
a proxy server inside the cluster (util/client/ARCHITECTURE.md).  The
TPU-native build keeps the idea — client-side stubs, server-side real
ObjectRefs/ActorHandles — but rides the framework's own length-prefixed
RPC plane instead of gRPC, and maps stubs <-> real handles with pickle's
persistent-id hook instead of a protobuf IR.
"""

from __future__ import annotations

import io
import pickle

import cloudpickle

_PROTO = 5


class _ClientPickler(cloudpickle.CloudPickler):
    """cloudpickle that externalizes refs/handles via persistent_id."""

    def __init__(self, file, persist_fn):
        super().__init__(file, protocol=_PROTO)
        self._persist_fn = persist_fn

    def persistent_id(self, obj):
        return self._persist_fn(obj)


class _ClientUnpickler(pickle.Unpickler):
    def __init__(self, file, load_fn):
        super().__init__(file)
        self._load_fn = load_fn

    def persistent_load(self, pid):
        return self._load_fn(pid)


def dumps_with(obj, persist_fn) -> bytes:
    buf = io.BytesIO()
    _ClientPickler(buf, persist_fn).dump(obj)
    return buf.getvalue()


def loads_with(data: bytes, load_fn):
    return _ClientUnpickler(io.BytesIO(data), load_fn).load()
