"""Out-of-cluster client (the reference's "Ray Client", ray://).

Reference: python/ray/util/client + util/client/ARCHITECTURE.md — lets a
process that is NOT part of the cluster drive it through a single proxy
endpoint.  `connect()` returns a :class:`ClientAPI` mirroring the
ray_tpu module verbs (put/get/wait/remote/kill/...).
"""

from ray_tpu.util.client.server import ClientServer  # noqa: F401
from ray_tpu.util.client.worker import (  # noqa: F401
    ClientAPI,
    ClientActorHandle,
    ClientObjectRef,
)


def connect(address: str, timeout: float = 30.0) -> ClientAPI:
    """Connect to a running ClientServer at "host:port"."""
    host, port = address.rsplit(":", 1)
    return ClientAPI(host, int(port), timeout=timeout)
