"""User-defined metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py — Counter (:155), Histogram (:220),
Gauge (:295); C++ stats flow through the node agent to Prometheus
(SURVEY.md §5 metrics).  Here every process keeps a registry and pushes
snapshots into the GCS KV (ns="metrics"); the dashboard head renders the
Prometheus exposition text from those snapshots.
"""

from __future__ import annotations

import threading
import time

from ray_tpu._private import locksan
from typing import Dict, List, Optional, Tuple

_REGISTRY_LOCK = locksan.make_lock("metrics._REGISTRY_LOCK")
_REGISTRY: Dict[str, "Metric"] = {}

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10]


class Metric:
    _kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        if not name:
            raise ValueError("metric name required")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        # label-values-tuple -> scalar (or bucket-counts for histograms)
        self._values: Dict[tuple, float] = {}
        self._lock = locksan.make_lock("Metric._lock")
        with _REGISTRY_LOCK:
            _REGISTRY[name] = self

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _label_values(self, tags: Optional[Dict[str, str]]) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(f"unknown tag keys {extra} for {self.name} "
                             f"(declared: {self.tag_keys})")
        return tuple(merged.get(k, "") for k in self.tag_keys)

    def snapshot(self) -> dict:
        with self._lock:
            return {"name": self.name, "kind": self._kind,
                    "description": self.description,
                    "tag_keys": self.tag_keys,
                    "values": dict(self._values),
                    "ts": time.time()}

    def series(self, tags: Optional[Dict[str, str]] = None) -> "_Series":
        """Pre-resolved handle for ONE label combination: set()/inc()
        without the per-call tag merge/validation (hot paths — e.g. the
        serve router updates its gauges on every request).  The handle
        registers the series eagerly so it appears in snapshots even
        before the first write."""
        key = self._label_values(tags)
        with self._lock:
            self._values.setdefault(key, 0.0)
        return _Series(self._values, key, self._lock)


class _Series:
    """Single-series view of a metric.  set() is one dict store on a
    pre-existing key — atomic under the GIL, so it takes no lock (the
    snapshot path copies the dict, which is likewise GIL-atomic).
    inc() is a read-modify-write and DOES take the metric's lock."""

    __slots__ = ("_values", "_key", "_lock")

    def __init__(self, values: Dict[tuple, float], key: tuple, lock):
        self._values = values
        self._key = key
        self._lock = lock

    def set(self, value: float):
        self._values[self._key] = float(value)

    def inc(self, value: float = 1.0):
        with self._lock:
            self._values[self._key] = \
                self._values.get(self._key, 0.0) + value


class Counter(Metric):
    _kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = self._label_values(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    _kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._label_values(tags)
        with self._lock:
            self._values[key] = float(value)


class Histogram(Metric):
    _kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description, tag_keys)
        self.boundaries = list(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        key = self._label_values(tags)
        with self._lock:
            entry = self._values.get(key)
            if not isinstance(entry, dict):
                entry = self._values[key] = {
                    "buckets": [0] * (len(self.boundaries) + 1),
                    "sum": 0.0, "count": 0}
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            entry["buckets"][idx] += 1
            entry["sum"] += value
            entry["count"] += 1

    def snapshot(self) -> dict:
        snap = super().snapshot()
        snap["boundaries"] = self.boundaries
        return snap


def registry_snapshot() -> List[dict]:
    with _REGISTRY_LOCK:
        metrics = list(_REGISTRY.values())
    return [m.snapshot() for m in metrics]


def prometheus_text(snapshots: List[dict]) -> str:
    """Render snapshots (possibly from many processes) as Prometheus
    exposition text (reference: _private/prometheus_exporter.py)."""
    by_name: Dict[str, List[dict]] = {}
    for s in snapshots:
        by_name.setdefault(s["name"], []).append(s)
    out: List[str] = []
    for name, snaps in sorted(by_name.items()):
        first = snaps[0]
        kind = first["kind"] if first["kind"] != "untyped" else "gauge"
        if first.get("description"):
            out.append(f"# HELP {name} {first['description']}")
        out.append(f"# TYPE {name} {kind}")
        for s in snaps:
            keys = s["tag_keys"]
            for label_vals, val in s["values"].items():
                labels = ",".join(
                    f'{k}="{v}"' for k, v in zip(keys, label_vals) if v)
                suffix = "{" + labels + "}" if labels else ""
                if isinstance(val, dict):  # histogram
                    cum = 0
                    for b, cnt in zip(s["boundaries"], val["buckets"]):
                        cum += cnt
                        lb = (labels + "," if labels else "") + f'le="{b}"'
                        out.append(f"{name}_bucket{{{lb}}} {cum}")
                    lb = (labels + "," if labels else "") + 'le="+Inf"'
                    out.append(f"{name}_bucket{{{lb}}} {val['count']}")
                    out.append(f"{name}_sum{suffix} {val['sum']}")
                    out.append(f"{name}_count{suffix} {val['count']}")
                else:
                    out.append(f"{name}{suffix} {val}")
    return "\n".join(out) + "\n"
