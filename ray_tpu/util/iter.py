"""ParallelIterator: sharded lazy iterators over actors.

Reference: python/ray/util/iter.py — `from_items/from_range/
from_iterators` build a ParallelIterator of N shards hosted by
ParallelIteratorWorker actors; transforms (`for_each/filter/batch/
flatten`) compose lazily per shard; `gather_sync/gather_async`
repatriate elements to a LocalIterator on the driver; `union`
concatenates iterators shard-wise.

Re-designed over this runtime's actor model:

* Transforms are DRIVER-SIDE pending descriptions (like the
  reference): deriving an iterator never mutates its parent, so
  ``base.for_each(f)`` and ``base.filter(g)`` are independent
  pipelines over the same source actors.
* Each gather opens a fresh iteration *epoch* on the shard actors
  (source rebuilt + that iterator's transform stack installed), so
  concurrent gathers — even over iterators sharing actors — never
  interleave state.
* ``next_batch`` pulls a bounded chunk per RPC, amortizing the
  per-call overhead the reference pays per element.

Lifetime: shard actors live until ``stop()`` (or cluster shutdown);
iterators over the same source share them, so stop only when every
derived iterator is done.

Scope note: ``local_shuffle``, ``repartition``, and the reference's
metrics contexts are not implemented; the core sharded-transform-
gather contract (what RLlib's legacy pipelines consumed) is.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Iterable, Iterator, List, Tuple, TypeVar

import ray_tpu

T = TypeVar("T")
U = TypeVar("U")

# Elements pulled per shard RPC: big enough to amortize call overhead,
# small enough to bound driver memory during gathers.
_CHUNK = 64

# Live epochs kept per shard actor; beyond this, the oldest ABANDONED
# gather's iterator state is dropped (an active gather hitting this
# limit is unsupported — documented, not silent: 16 concurrent gathers
# over one source is far outside the intended use).
_MAX_EPOCHS = 16


class _Done:
    """Sentinel marking shard exhaustion (picklable)."""


def _apply_transform(kind: str, fn, it: Iterable) -> Iterable:
    # Bound per stage — a bare generator expression in the caller's
    # loop would capture the loop variables by reference and lazily
    # apply the LAST transform at every stage.
    if kind == "for_each":
        return (fn(x) for x in it)
    if kind == "filter":
        return (x for x in it if fn(x))
    if kind == "batch":
        return _batched(it, fn)
    if kind == "flatten":
        return (y for x in it for y in x)
    if kind == "combine":
        return (y for x in it for y in fn(x))
    raise ValueError(f"unknown transform {kind!r}")


def _batched(it: Iterable, n: int):
    buf: List = []
    for x in it:
        buf.append(x)
        if len(buf) >= n:
            yield buf
            buf = []
    if buf:
        yield buf


class _ShardWorker:
    """Actor hosting one shard's source; transform stacks arrive per
    epoch, so the actor itself is immutable between gathers."""

    def __init__(self, make_source):
        self._make_source = make_source
        self._epochs: dict = {}

    def start_epoch(self, epoch: str, transforms: List[Tuple[str, Any]]):
        it: Iterable = self._make_source()
        for kind, fn in transforms:
            it = _apply_transform(kind, fn, it)
        self._epochs[epoch] = iter(it)
        while len(self._epochs) > _MAX_EPOCHS:
            self._epochs.pop(next(iter(self._epochs)))

    def next_batch(self, epoch: str, n: int = _CHUNK):
        """Up to n transformed elements, or _Done when exhausted."""
        it = self._epochs.get(epoch)
        if it is None:
            return _Done()
        out = []
        for x in it:
            out.append(x)
            if len(out) >= n:
                break
        if not out:
            self._epochs.pop(epoch, None)
            return _Done()
        return out


class LocalIterator:
    """Driver-side iterator (reference: iter.py:705 LocalIterator).

    Build-once semantics like the reference: ``__iter__`` and
    ``__next__`` share one underlying stream, so mixing protocols (or
    two loops over the same object) consume the SAME elements instead
    of silently restarting the gather.  Derived iterators
    (``for_each``...) build fresh from the factory."""

    def __init__(self, gen_factory: Callable[[], Iterator]):
        self._factory = gen_factory
        self._it: Iterator | None = None

    def _build_once(self) -> Iterator:
        if self._it is None:
            self._it = self._factory()
        return self._it

    def __iter__(self):
        self._build_once()
        return self

    def __next__(self):
        return next(self._build_once())

    def for_each(self, fn) -> "LocalIterator":
        factory = self._factory
        return LocalIterator(lambda: (fn(x) for x in factory()))

    def filter(self, fn) -> "LocalIterator":
        factory = self._factory
        return LocalIterator(lambda: (x for x in factory() if fn(x)))

    def batch(self, n: int) -> "LocalIterator":
        factory = self._factory
        return LocalIterator(lambda: _batched(factory(), n))

    def take(self, n: int) -> List:
        out = []
        for x in self:
            out.append(x)
            if len(out) >= n:
                break
        return out


class ParallelIterator:
    """A sharded iterator (reference: iter.py:132).  Holds (actor,
    transform-stack) pairs only — deriving creates a new object and
    never touches actor state, so branches and unions are
    independent.  Serializable."""

    def __init__(self, shards: List[Tuple[Any, Tuple]], name: str):
        self._shards = shards
        self.name = name

    def __repr__(self):
        return f"ParallelIterator[{self.name}, {len(self._shards)} shards]"

    def num_shards(self) -> int:
        return len(self._shards)

    def stop(self) -> None:
        """Kill the shard actors.  Iterators derived from (or
        union-ed with) this one share them — stop only when all are
        done."""
        for actor, _ in self._shards:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass

    # --- lazy transforms (pending descriptions) ----------------------
    def _with(self, kind: str, fn, label: str) -> "ParallelIterator":
        shards = [(a, t + ((kind, fn),)) for a, t in self._shards]
        return ParallelIterator(shards, f"{self.name}.{label}")

    def for_each(self, fn: Callable[[T], U]) -> "ParallelIterator":
        return self._with("for_each", fn, "for_each()")

    def filter(self, fn: Callable[[T], bool]) -> "ParallelIterator":
        return self._with("filter", fn, "filter()")

    def batch(self, n: int) -> "ParallelIterator":
        return self._with("batch", n, f"batch({n})")

    def flatten(self) -> "ParallelIterator":
        return self._with("flatten", None, "flatten()")

    def combine(self, fn: Callable[[T], List[U]]) -> "ParallelIterator":
        return self._with("combine", fn, "combine()")

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        """Shard-wise concatenation; each side keeps its own transform
        stack (reference: iter.py:600)."""
        return ParallelIterator(self._shards + other._shards,
                                f"{self.name}.union({other.name})")

    def select_shards(self, keep: List[int]) -> "ParallelIterator":
        return ParallelIterator([self._shards[i] for i in keep],
                                f"{self.name}.select_shards({keep})")

    # --- gathers -----------------------------------------------------
    def _open_epoch(self) -> List[Tuple[Any, str]]:
        """Per-SHARD epoch keys: a union can list the same actor
        twice with different transform stacks, so one shared key
        would make the second start_epoch overwrite the first."""
        base = uuid.uuid4().hex
        keyed = [(a, f"{base}:{i}")
                 for i, (a, _) in enumerate(self._shards)]
        ray_tpu.get([a.start_epoch.remote(key, list(t))
                     for (a, t), (_, key) in zip(self._shards, keyed)],
                    timeout=120)
        return keyed

    def gather_sync(self) -> LocalIterator:
        """Round-robin across shards in order: one chunk per shard per
        round (the reference gather_sync's deterministic interleave,
        at chunk granularity)."""

        def gen():
            live = self._open_epoch()
            while live:
                nxt = []
                for a, key in live:
                    chunk = ray_tpu.get(a.next_batch.remote(key))
                    if isinstance(chunk, _Done):
                        continue
                    yield from chunk
                    nxt.append((a, key))
                live = nxt
        return LocalIterator(gen)

    def gather_async(self) -> LocalIterator:
        """One in-flight request per shard; yields whichever shard's
        chunk lands first (reference gather_async(num_async=1))."""

        def gen():
            keyed = self._open_epoch()
            pending = {a.next_batch.remote(key): (a, key)
                       for a, key in keyed}
            while pending:
                ready, _ = ray_tpu.wait(list(pending), num_returns=1,
                                        timeout=60)
                if not ready:
                    # Nothing in 60s: either a shard died (get raises
                    # its error) or it is genuinely slow (timeout ->
                    # keep waiting).  Never spin silently on a dead
                    # ref.
                    try:
                        ray_tpu.get(list(pending), timeout=1)
                    except ray_tpu.GetTimeoutError:
                        pass
                    continue
                for ref in ready:
                    a, key = pending.pop(ref)
                    chunk = ray_tpu.get(ref)
                    if isinstance(chunk, _Done):
                        continue
                    pending[a.next_batch.remote(key)] = (a, key)
                    yield from chunk
        return LocalIterator(gen)

    def take(self, n: int) -> List:
        return self.gather_sync().take(n)

    def show(self, n: int = 20) -> None:
        for x in self.take(n):
            print(x)


def _make_shard_actors(sources: List[Callable[[], Iterable]],
                      name: str) -> ParallelIterator:
    cls = ray_tpu.remote(_ShardWorker)
    return ParallelIterator(
        [(cls.options(num_cpus=0.1).remote(src), ()) for src in sources],
        name)


def from_iterators(generators: List[Callable[[], Iterable] | Iterable],
                   name: str = "from_iterators"
                   ) -> ParallelIterator:
    """One shard per element; each may be an iterable or a zero-arg
    callable returning one (reference: iter.py:75)."""
    sources = []
    for g in generators:
        if callable(g):
            sources.append(g)
        else:
            items = list(g)
            sources.append(lambda items=items: items)
    return _make_shard_actors(sources, name)


def from_items(items: List[T], num_shards: int = 2,
               name: str | None = None) -> ParallelIterator:
    """Partition a list over num_shards shard actors (reference:
    iter.py:18)."""
    shards: List[List] = [[] for _ in range(num_shards)]
    for i, item in enumerate(items):
        shards[i % num_shards].append(item)
    return from_iterators(shards,
                          name or f"from_items[{len(items)}]")


def from_range(n: int, num_shards: int = 2,
               name: str | None = None) -> ParallelIterator:
    """range(n) split into contiguous per-shard subranges (reference:
    iter.py:43)."""
    sources = []
    per = n // num_shards
    for i in range(num_shards):
        start = i * per
        end = n if i == num_shards - 1 else (i + 1) * per
        sources.append(lambda s=start, e=end: range(s, e))
    return _make_shard_actors(sources, name or f"from_range[{n}]")
