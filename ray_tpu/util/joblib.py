"""Joblib backend: run joblib.Parallel workloads on the cluster.

Reference: python/ray/util/joblib/__init__.py (+ ray_backend.py) —
`register_ray()` registers a joblib parallel backend so existing
scikit-learn code (`GridSearchCV(n_jobs=-1)` etc.) fans its work units
out as cluster tasks under `with parallel_backend("ray"):` — zero
changes to the sklearn code itself.

Re-designed over this runtime's cheap-task path: each joblib batch
(a list of pickled closures) becomes one remote task; effective
parallelism follows the cluster's CPU pool rather than local
processes.
"""

from __future__ import annotations

from typing import List


def _run_batch(items: List):
    """One joblib batch: items are (func, args, kwargs) triples (the
    payload of joblib's BatchedCalls), or bare callables."""
    out = []
    for it in items:
        if callable(it):
            out.append(it())
        else:
            fn, args, kwargs = it
            out.append(fn(*args, **kwargs))
    return out


from joblib._parallel_backends import ParallelBackendBase


class RayBackend(ParallelBackendBase):
    """joblib ParallelBackendBase implementation over remote tasks."""

    supports_timeout = True
    supports_retrieve_callback = False

    def __init__(self, nesting_level=None, inner_n_threads=None, **_kw):
        super().__init__(nesting_level=nesting_level)
        self.parallel = None
        self._n_jobs = 1

    # --- joblib backend protocol ------------------------------------
    def configure(self, n_jobs=1, parallel=None, **_kw):
        import ray_tpu
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self.parallel = parallel
        self._n_jobs = self.effective_n_jobs(n_jobs)
        self._task = ray_tpu.remote(_run_batch)
        return self._n_jobs

    def effective_n_jobs(self, n_jobs):
        import ray_tpu
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        total = int(ray_tpu.cluster_resources().get("CPU", 1)) \
            if ray_tpu.is_initialized() else 1
        if n_jobs is None:
            return 1
        if n_jobs < 0:
            return max(1, total + 1 + n_jobs)
        return min(n_jobs, max(total, 1))

    def apply_async(self, func, callback=None):
        """func is a joblib BatchedCalls (callable returning the list
        of results); ship it as one task."""
        import ray_tpu
        ref = self._task.remote(list(func.items)
                                if hasattr(func, "items") else [func])
        return _AsyncResult(ref, callback)

    def get_nested_backend(self):
        from joblib._parallel_backends import SequentialBackend
        return SequentialBackend(nesting_level=1), None

    def abort_everything(self, ensure_ready=True):
        pass

    def terminate(self):
        pass


class _AsyncResult:
    def __init__(self, ref, callback):
        self._ref = ref
        self._callback = callback
        self._done = False
        self._result = None

    def get(self, timeout=None):
        import ray_tpu
        if not self._done:
            self._result = ray_tpu.get(self._ref,
                                       timeout=timeout or 600)
            self._done = True
            if self._callback is not None:
                self._callback(self._result)
        return self._result


def register_ray() -> None:
    """Make `parallel_backend("ray")` available (reference:
    util/joblib register_ray)."""
    from joblib import register_parallel_backend
    register_parallel_backend("ray", RayBackend)
