"""multiprocessing.Pool drop-in over cluster tasks.

Reference: python/ray/util/multiprocessing — Pool whose apply/map/starmap
run as remote tasks, so existing Pool code scales past one machine
without changes.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu

_GET_TIMEOUT = 3600.0


class AsyncResult:
    def __init__(self, refs, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None) -> Any:
        out = ray_tpu.get(self._refs, timeout=timeout or _GET_TIMEOUT)
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0.001)
            return True
        except Exception:
            return False


class Pool:
    """Pool(processes) — processes bounds in-flight tasks, not workers
    (the cluster supplies the workers)."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self._max_inflight = processes or 64
        self._closed = False
        if initializer is not None:
            initializer(*initargs)
        self._remote_cache: dict = {}

    def _remote(self, fn):
        rf = self._remote_cache.get(fn)
        if rf is None:
            rf = self._remote_cache[fn] = ray_tpu.remote(fn)
        return rf

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    def apply(self, fn: Callable, args: tuple = (), kwds: dict = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args: tuple = (), kwds: dict = None):
        self._check_open()
        ref = self._remote(fn).remote(*args, **(kwds or {}))
        return AsyncResult([ref], single=True)

    def _submit_all(self, fn, iterables) -> List:
        rf = self._remote(fn)
        refs = []
        inflight: List = []
        for args in iterables:
            if len(inflight) >= self._max_inflight:
                _, inflight = ray_tpu.wait(
                    inflight, num_returns=1, timeout=_GET_TIMEOUT)
                inflight = list(inflight)
            ref = rf.remote(*args)
            refs.append(ref)
            inflight.append(ref)
        return refs

    def map(self, fn: Callable, iterable: Iterable) -> List:
        return self.map_async(fn, iterable).get()

    def map_async(self, fn: Callable, iterable: Iterable) -> AsyncResult:
        self._check_open()
        return AsyncResult(
            self._submit_all(fn, ((x,) for x in iterable)), single=False)

    def starmap(self, fn: Callable, iterable: Iterable) -> List:
        self._check_open()
        return AsyncResult(self._submit_all(fn, iterable),
                           single=False).get()

    def imap(self, fn: Callable, iterable: Iterable):
        self._check_open()
        for ref in self._submit_all(fn, ((x,) for x in iterable)):
            yield ray_tpu.get(ref, timeout=_GET_TIMEOUT)

    def imap_unordered(self, fn: Callable, iterable: Iterable):
        self._check_open()
        pending = self._submit_all(fn, ((x,) for x in iterable))
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1,
                                         timeout=_GET_TIMEOUT)
            pending = list(pending)
            for ref in done:
                yield ray_tpu.get(ref, timeout=_GET_TIMEOUT)

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False
