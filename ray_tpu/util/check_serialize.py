"""Debug helper: find WHICH captured object makes a closure/instance
unpicklable (reference: python/ray/util/check_serialize.py
inspect_serializability:146 — same recursive frame-walk idea, formatted
without the colorama dependency)."""

from __future__ import annotations

import inspect
from typing import Any, Optional, Set, Tuple

import cloudpickle

# Constructors whose results can essentially never survive cloudpickle:
# they wrap OS handles or interpreter-internal state.  Keyed by
# (module, callable); module None means the builtin namespace.  Shared
# by the static linter (ray_tpu.lint rule RTL006, which flags remote
# closures capturing a value built by one of these) and by the dynamic
# inspector below (which uses it to explain WHY a leaf failed).
KNOWN_UNSERIALIZABLE_CONSTRUCTORS = {
    ("threading", "Lock"): "thread locks wrap an OS mutex",
    ("threading", "RLock"): "thread locks wrap an OS mutex",
    ("threading", "Condition"): "condition variables wrap an OS mutex",
    ("threading", "Semaphore"): "semaphores wrap an OS mutex",
    ("threading", "BoundedSemaphore"): "semaphores wrap an OS mutex",
    ("threading", "Event"): "events wrap an OS mutex",
    ("threading", "Thread"): "thread objects wrap an OS thread",
    ("threading", "local"): "thread-local storage is per-interpreter",
    ("multiprocessing", "Lock"): "process locks wrap an OS semaphore",
    ("multiprocessing", "RLock"): "process locks wrap an OS semaphore",
    ("multiprocessing", "Queue"): "mp queues hold pipes + feeder threads",
    ("multiprocessing", "Pool"): "process pools hold live child processes",
    (None, "open"): "file objects hold an OS file descriptor",
    ("io", "open"): "file objects hold an OS file descriptor",
    ("socket", "socket"): "sockets hold an OS file descriptor",
    ("socket", "create_connection"): "sockets hold an OS file descriptor",
    ("sqlite3", "connect"): "database connections hold an OS handle",
    ("subprocess", "Popen"): "process handles wrap a live child process",
    ("asyncio", "get_event_loop"): "event loops hold OS selectors",
    ("asyncio", "new_event_loop"): "event loops hold OS selectors",
}

# Runtime type names the dynamic path recognizes without pickling:
# maps (type module, type name) -> reason.
_KNOWN_UNSERIALIZABLE_TYPES = {
    ("_thread", "lock"): "thread locks wrap an OS mutex",
    ("_thread", "RLock"): "thread locks wrap an OS mutex",
    ("_thread", "_local"): "thread-local storage is per-interpreter",
    ("_io", "TextIOWrapper"): "file objects hold an OS file descriptor",
    ("_io", "BufferedReader"): "file objects hold an OS file descriptor",
    ("_io", "BufferedWriter"): "file objects hold an OS file descriptor",
    ("_io", "FileIO"): "file objects hold an OS file descriptor",
    ("socket", "socket"): "sockets hold an OS file descriptor",
    ("sqlite3", "Connection"): "database connections hold an OS handle",
    ("subprocess", "Popen"): "process handles wrap a live child process",
    ("builtins", "generator"): "generators capture a paused stack frame",
    ("builtins", "coroutine"): "coroutines capture a paused stack frame",
}


def describe_unserializable(obj: Any) -> Optional[str]:
    """A human reason when `obj` is a KNOWN-unserializable kind (lock,
    file handle, generator, ...); None when we have nothing special to
    say and the generic pickling error stands on its own."""
    t = type(obj)
    return _KNOWN_UNSERIALIZABLE_TYPES.get(
        (getattr(t, "__module__", ""), t.__name__))


class FailureTuple:
    """One serialization failure frame: the failing object, the variable
    name that references it, and the parent holding that reference."""

    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return (f"FailTuple({self.name} "
                f"[obj={self.obj!r}, parent={self.parent!r}])")


def _check(obj) -> bool:
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:
        return False


def _inspect_function(fn, depth, parent, failures, log):
    closure = inspect.getclosurevars(fn)
    found = False
    for kind, mapping in (("global", closure.globals),
                          ("closure-captured", closure.nonlocals)):
        for name, obj in mapping.items():
            if _check(obj):
                continue
            reason = describe_unserializable(obj)
            log.append(f"{'  ' * depth}{kind} variable {name!r} in "
                       f"{fn.__qualname__} fails serialization"
                       + (f" ({reason})" if reason else ""))
            found = True
            if depth > 0:
                _walk(obj, name, depth - 1, fn, failures, log)
            else:
                failures.add_frame(obj, name, fn)
    return found


def _inspect_object(obj, depth, parent, failures, log):
    members = getattr(obj, "__dict__", None)
    found = False
    if isinstance(members, dict):
        for name, attr in members.items():
            if _check(attr):
                continue
            reason = describe_unserializable(attr)
            log.append(f"{'  ' * depth}attribute {name!r} of "
                       f"{type(obj).__name__} fails serialization"
                       + (f" ({reason})" if reason else ""))
            found = True
            if depth > 0:
                _walk(attr, name, depth - 1, obj, failures, log)
            else:
                failures.add_frame(attr, name, obj)
    return found


class _Failures:
    def __init__(self):
        self.set: Set[FailureTuple] = set()
        self._seen = set()

    def add_frame(self, obj, name, parent):
        key = (id(obj), name)
        if key not in self._seen:
            self._seen.add(key)
            self.set.add(FailureTuple(obj, name, parent))


def _walk(obj, name, depth, parent, failures, log):
    if inspect.isfunction(obj):
        found = _inspect_function(obj, depth, parent, failures, log)
    else:
        found = _inspect_object(obj, depth, parent, failures, log)
    if not found:
        # The object itself is the leaf cause.
        failures.add_frame(obj, name, parent)


def inspect_serializability(
        base_obj: Any, name: Optional[str] = None, depth: int = 3,
        print_file=None) -> Tuple[bool, Set[FailureTuple]]:
    """Identify what about `base_obj` fails cloudpickle serialization.

    Returns (serializable, failure_frames).  Output mirrors the
    reference's tree report but to a plain list of lines."""
    name = name or getattr(base_obj, "__qualname__", repr(base_obj))
    failures = _Failures()
    log: list = []
    ok = _check(base_obj)
    if not ok:
        log.insert(0, f"Checking serializability of {name!r}: FAILED")
        _walk(base_obj, name, depth, None, failures, log)
    else:
        log.insert(0, f"Checking serializability of {name!r}: OK")
    text = "\n".join(log)
    if print_file is not None:
        print(text, file=print_file)
    elif not ok:
        print(text)
    return ok, failures.set
