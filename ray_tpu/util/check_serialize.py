"""Debug helper: find WHICH captured object makes a closure/instance
unpicklable (reference: python/ray/util/check_serialize.py
inspect_serializability:146 — same recursive frame-walk idea, formatted
without the colorama dependency)."""

from __future__ import annotations

import inspect
from typing import Any, Optional, Set, Tuple

import cloudpickle


class FailureTuple:
    """One serialization failure frame: the failing object, the variable
    name that references it, and the parent holding that reference."""

    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return (f"FailTuple({self.name} "
                f"[obj={self.obj!r}, parent={self.parent!r}])")


def _check(obj) -> bool:
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:
        return False


def _inspect_function(fn, depth, parent, failures, log):
    closure = inspect.getclosurevars(fn)
    found = False
    for kind, mapping in (("global", closure.globals),
                          ("closure-captured", closure.nonlocals)):
        for name, obj in mapping.items():
            if _check(obj):
                continue
            log.append(f"{'  ' * depth}{kind} variable {name!r} in "
                       f"{fn.__qualname__} fails serialization")
            found = True
            if depth > 0:
                _walk(obj, name, depth - 1, fn, failures, log)
            else:
                failures.add_frame(obj, name, fn)
    return found


def _inspect_object(obj, depth, parent, failures, log):
    members = getattr(obj, "__dict__", None)
    found = False
    if isinstance(members, dict):
        for name, attr in members.items():
            if _check(attr):
                continue
            log.append(f"{'  ' * depth}attribute {name!r} of "
                       f"{type(obj).__name__} fails serialization")
            found = True
            if depth > 0:
                _walk(attr, name, depth - 1, obj, failures, log)
            else:
                failures.add_frame(attr, name, obj)
    return found


class _Failures:
    def __init__(self):
        self.set: Set[FailureTuple] = set()
        self._seen = set()

    def add_frame(self, obj, name, parent):
        key = (id(obj), name)
        if key not in self._seen:
            self._seen.add(key)
            self.set.add(FailureTuple(obj, name, parent))


def _walk(obj, name, depth, parent, failures, log):
    if inspect.isfunction(obj):
        found = _inspect_function(obj, depth, parent, failures, log)
    else:
        found = _inspect_object(obj, depth, parent, failures, log)
    if not found:
        # The object itself is the leaf cause.
        failures.add_frame(obj, name, parent)


def inspect_serializability(
        base_obj: Any, name: Optional[str] = None, depth: int = 3,
        print_file=None) -> Tuple[bool, Set[FailureTuple]]:
    """Identify what about `base_obj` fails cloudpickle serialization.

    Returns (serializable, failure_frames).  Output mirrors the
    reference's tree report but to a plain list of lines."""
    name = name or getattr(base_obj, "__qualname__", repr(base_obj))
    failures = _Failures()
    log: list = []
    ok = _check(base_obj)
    if not ok:
        log.insert(0, f"Checking serializability of {name!r}: FAILED")
        _walk(base_obj, name, depth, None, failures, log)
    else:
        log.insert(0, f"Checking serializability of {name!r}: OK")
    text = "\n".join(log)
    if print_file is not None:
        print(text, file=print_file)
    elif not ok:
        print(text)
    return ok, failures.set
