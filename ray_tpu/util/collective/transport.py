"""Peer-to-peer bulk-data plane for host collectives.

The coordinator actor (collective.py) is rendezvous + small-tensor
reductions ONLY; every bulk tensor chunk moves member-to-member through
this transport, which lives inside each member's CoreWorker process and
speaks the runtime's own data-plane idioms (reference architecture: the
NCCL collective group's dedicated comm plane in
collective_group/nccl_collective_group.py:127 — rendezvous through a
named store actor, data through its own channel):

* **Same-host path** — every member owns a sparse scratch arena in
  /dev/shm (token-stamped so a path collision on another host can never
  be mistaken for shared memory).  A chunk send is ONE memcpy into the
  sender's arena plus a tiny ``coll_ctl`` descriptor RPC; the receiver
  maps the peer arena read-only and reduces/copies STRAIGHT OUT of it
  (``np.frombuffer`` over the mapping — no socket, no staging buffer).
  The ctl reply doubles as the slot ack: it is sent only after the
  receiver consumed the bytes, so the sender's scratch region can be
  recycled the moment the request resolves.
* **Wire path** — chunks ride raw ``KIND_BLOB`` frames worker-to-worker
  (``coll_chunk``), payload handed to the transport as one memoryview
  and landed by the receiver's blob provider DIRECTLY in the
  destination tensor when the receive was posted first (the same
  zero-staging-copy receive as the object transfer plane).  Chunks
  larger than ``cfg.collective_chunk_bytes`` are split and pumped
  through the transfer plane's shared sliding window
  (``transfer.run_windowed``, ``cfg.transfer_window_chunks`` in
  flight).
* **Failure plane** — the coordinator pushes ``coll_ctl abort`` frames
  at member endpoints when the group dies (member death, destroy while
  ops are in flight); the transport fails every pending receive with a
  structured :class:`CollectiveGroupError` instead of letting peers
  hang to the collective timeout.  A dead peer's closed connection
  fails in-flight sends the same way.

Threading: collective ops run on a per-group op thread (collective.py);
the transport bridges to the CoreWorker IO loop with
``run_coroutine_threadsafe``.  Chunk payload memcpys and reductions
happen on the op thread — the IO loop only moves descriptors and socket
bytes.
"""

from __future__ import annotations

import asyncio
import logging
import mmap
import os
import threading
import time
from collections import OrderedDict

import numpy as np

from ray_tpu._private import failpoints, locksan, protocol, transfer
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu.util.collective.types import CollectiveGroupError

logger = logging.getLogger(__name__)

_TOKEN_LEN = 16
_HEADER = 64  # scratch arena bytes reserved for the token stamp
_ALIGN = 64

# Bounded memory of aborted groups (late frames for them are refused,
# not silently restashed); oldest marks age out.
_MAX_ABORT_MARKS = 64


def _remain(deadline):
    if deadline is None:
        return None
    return max(0.001, deadline - time.monotonic())


def _scratch_dir() -> str:
    shm = "/dev/shm"
    if os.path.isdir(shm) and os.access(shm, os.W_OK):
        return shm
    import tempfile
    return tempfile.gettempdir()


# ---------------------------------------------------------- one-sided reads
# process_vm_readv: copy bytes STRAIGHT out of a same-host peer's address
# space (same uid) — the chunk is never staged anywhere, the sender does
# zero work per byte, and none of the shared-mapping page-fault/TLB
# pathologies of a shared arena apply (hardened kernels charge ~100x an
# anon fault for first touches of shared file pages).  Gated by a probe
# at rendezvous (Yama ptrace_scope et al. can forbid it), with the
# scratch-arena path as the fallback.
_libc = None


def _get_libc():
    global _libc
    if _libc is None:
        import ctypes
        lib = ctypes.CDLL(None, use_errno=True)
        lib.process_vm_readv.restype = ctypes.c_ssize_t
        _libc = lib
    return _libc


def pvm_read_into(pid: int, remote_addr: int, dest_addr: int, n: int):
    """Read n bytes from (pid, remote_addr) into local dest_addr.
    Raises OSError when the kernel forbids or the peer is gone."""
    import ctypes

    class _IOVec(ctypes.Structure):
        _fields_ = [("iov_base", ctypes.c_void_p),
                    ("iov_len", ctypes.c_size_t)]

    lib = _get_libc()
    pos = 0
    while pos < n:
        liov = _IOVec(dest_addr + pos, n - pos)
        riov = _IOVec(remote_addr + pos, n - pos)
        got = lib.process_vm_readv(pid, ctypes.byref(liov), 1,
                                   ctypes.byref(riov), 1, 0)
        if got <= 0:
            err = ctypes.get_errno()
            raise OSError(err, f"process_vm_readv(pid={pid}): "
                               f"{os.strerror(err)}")
        pos += got


class Endpoint:
    """One member's data-plane address, as exchanged at rendezvous."""

    __slots__ = ("rank", "addr", "node_id", "scratch_path",
                 "scratch_token", "pid", "actor_id", "same_host", "pvm",
                 "pvm_addr")

    def __init__(self, info: dict):
        self.rank = info["rank"]
        self.addr = tuple(info["addr"])
        self.node_id = info.get("node_id")
        self.scratch_path = info.get("scratch_path")
        self.scratch_token = info.get("scratch_token")
        self.pid = info.get("pid")
        self.actor_id = info.get("actor_id")
        self.pvm_addr = info.get("pvm_addr")
        self.same_host = False  # filled in by prepare_group
        self.pvm = False        # one-sided reads allowed (prepare_group)


class ScratchArena:
    """Sender-side shared scratch: one sparse token-stamped mmap file
    per member process.  A first-fit free list hands out chunk slots;
    ``alloc`` blocks (bounded) when concurrent ops have the arena full,
    because slots recycle as soon as receivers ack."""

    def __init__(self, path: str, capacity: int):
        self.path = path
        self.capacity = max(capacity, _HEADER + _ALIGN)
        self.token = os.urandom(_TOKEN_LEN)
        fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
        try:
            os.ftruncate(fd, self.capacity)
            self._mm = mmap.mmap(fd, self.capacity)
        finally:
            os.close(fd)
        self._mm[0:_TOKEN_LEN] = self.token
        self._free = [(_HEADER, self.capacity - _HEADER)]
        self._cond = locksan.make_condition("ScratchArena._cond")

    @property
    def token_hex(self) -> str:
        return self.token.hex()

    def alloc(self, n: int, deadline) -> int:
        n = max(_ALIGN, (n + _ALIGN - 1) // _ALIGN * _ALIGN)
        with self._cond:
            while True:
                for i, (off, sz) in enumerate(self._free):
                    if sz >= n:
                        if sz == n:
                            self._free.pop(i)
                        else:
                            self._free[i] = (off + n, sz - n)
                        return off
                remain = _remain(deadline)
                if remain is not None and remain <= 0.002:
                    raise CollectiveGroupError(
                        "?", "collective scratch arena exhausted "
                        f"({self.capacity} bytes; raise "
                        "RT_COLLECTIVE_SCRATCH_BYTES or shrink buckets)")
                if not self._cond.wait(
                        min(remain, 1.0) if remain is not None else 1.0):
                    continue

    def free(self, off: int, n: int):
        n = max(_ALIGN, (n + _ALIGN - 1) // _ALIGN * _ALIGN)
        with self._cond:
            self._free.append((off, n))
            self._free.sort()
            merged = []
            for o, s in self._free:
                if merged and merged[-1][0] + merged[-1][1] == o:
                    merged[-1] = (merged[-1][0], merged[-1][1] + s)
                else:
                    merged.append((o, s))
            self._free = [tuple(m) for m in merged]
            self._cond.notify_all()

    def write(self, off: int, mv):
        self._mm[off:off + len(mv)] = mv

    def close(self):
        try:
            self._mm.close()
        except Exception:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class _PeerScratch:
    """Read-only mapping of a co-located peer's scratch arena."""

    def __init__(self, path: str, token_hex: str):
        size = os.path.getsize(path)
        fd = os.open(path, os.O_RDONLY)
        try:
            self._mm = mmap.mmap(fd, size, access=mmap.ACCESS_READ)
        finally:
            os.close(fd)
        if bytes(self._mm[0:_TOKEN_LEN]) != bytes.fromhex(token_hex):
            self._mm.close()
            raise OSError(f"scratch token mismatch at {path}")
        self.view = memoryview(self._mm)

    def close(self):
        try:
            self.view.release()
            self._mm.close()
        except Exception:
            pass


class RecvHandle:
    """One posted chunk receive.  ``wait_array`` blocks the op thread
    until the chunk landed and returns it as a numpy view — into the
    caller's own sink (wire fast path), into the PEER's scratch arena
    (same-host; read-only), or over a staged bytes (late-registration
    race).  ``release`` MUST be called after the bytes are consumed: it
    is what lets a same-host sender recycle its scratch slot."""

    def __init__(self, tr: "CollectiveTransport", key, nbytes: int,
                 deadline, cfut, sink_arr):
        self._tr = tr
        self._key = key
        self._nbytes = nbytes
        self._deadline = deadline
        self._cfut = cfut
        self._sink_arr = sink_arr
        self._payload = None
        self.delivered_in_place = False

    def wait_array(self, dtype) -> np.ndarray:
        grace = _remain(self._deadline)
        try:
            payload = self._cfut.result(
                None if grace is None else grace + 10.0)
        except CollectiveGroupError:
            raise
        except Exception as e:
            raise CollectiveGroupError(
                self._key[0], f"chunk receive failed for {self._key}: "
                f"{type(e).__name__}: {e}") from e
        self._payload = payload
        mode = payload[0]
        if mode == "sink":
            self.delivered_in_place = True
            return self._sink_arr
        if mode == "bytes":
            buf = payload[1]
            if len(buf) != self._nbytes:
                raise CollectiveGroupError(
                    self._key[0], f"short chunk for {self._key}: "
                    f"{len(buf)} of {self._nbytes} bytes")
            return np.frombuffer(buf, dtype=dtype)
        if mode == "pvm":
            # One-sided read: copy the chunk STRAIGHT out of the
            # sender's address space into the caller's sink (or a fresh
            # buffer), on the op thread.
            _, pid, addr, n, _x, _evt = payload
            if n != self._nbytes:
                raise CollectiveGroupError(
                    self._key[0], f"short pvm chunk for {self._key}: "
                    f"{n} of {self._nbytes} bytes")
            dst = self._sink_arr
            if dst is None:
                dst = np.empty(n // np.dtype(dtype).itemsize, dtype)
            else:
                self.delivered_in_place = True
            try:
                pvm_read_into(pid, addr, dst.ctypes.data, n)
            except OSError as e:
                raise CollectiveGroupError(
                    self._key[0], f"one-sided read from pid {pid} "
                    f"failed (peer died?): {e}") from e
            return dst if dst.dtype == np.dtype(dtype) \
                else dst.view(dtype)
        # ("shm", path, tok, off, n, evt)
        _, path, tok, off, n, _evt = payload
        if n != self._nbytes:
            raise CollectiveGroupError(
                self._key[0], f"short shm chunk for {self._key}: "
                f"{n} of {self._nbytes} bytes")
        view = self._tr.peer_view(path, tok, off, n)
        return np.frombuffer(view, dtype=dtype)

    def release(self):
        payload, self._payload = self._payload, None
        if payload is not None and payload[0] in ("shm", "pvm"):
            # The ctl handler is awaiting this event; setting it sends
            # the reply that acks the sender's buffer/slot.
            self._tr.signal_done(payload[5])
        self._sink_arr = None


def _new_entry(group):
    return {"group": group, "fut": None, "sink": None, "via": None,
            "buf": None, "got": 0, "payload": None}


class CollectiveTransport:
    """Per-process data plane shared by every collective group member
    living in this CoreWorker."""

    def __init__(self, w):
        self.w = w
        self.scratch: ScratchArena | None = None
        self._peer_maps: dict[str, _PeerScratch] = {}
        self._entries: dict = {}         # key -> recv entry (loop-confined)
        self._aborted: "OrderedDict[str, str]" = OrderedDict()
        self._scratch_lock = locksan.make_lock(
            "CollectiveTransport._scratch_lock")
        # Sticky scratch slots, keyed (group, stream tag): each logical
        # send stream (e.g. "this group's reduce-scatter chunk to rank
        # p") keeps ONE stable arena offset across ops.  Page-fault
        # economics demand this: a first touch of a shared mapping
        # costs ~100x an anon fault under hardened/paravirt kernels, so
        # per-op alloc/free (drifting offsets) would re-fault every op
        # while sticky slots fault once and stay warm.  Safe because
        # ops within a group are serialized and every send is acked
        # before its op completes.
        self._sticky: dict = {}
        # Probe buffer for one-sided reads: peers validate that they
        # can process_vm_readv THIS process (and that pid+address refer
        # to who they think) by reading these 16 bytes and comparing
        # with the token from the endpoint table.
        self._pvm_token = os.urandom(_TOKEN_LEN)
        self._pvm_probe = np.frombuffer(bytearray(self._pvm_token),
                                        dtype=np.uint8)
        w.ext_rpc["coll_ctl"] = self._rpc_ctl
        w.ext_rpc["coll_chunk"] = self._rpc_chunk
        w.blob_providers["coll_chunk"] = self._blob_sink

    # ------------------------------------------------------------ endpoints
    def endpoint_info(self, rank: int) -> dict:
        scratch = self._ensure_scratch()
        w = self.w
        nid = getattr(w.node_id, "hex", None)
        aid = getattr(w.actor_id, "hex", None)
        return {
            "rank": rank,
            "addr": list(w.addr),
            "node_id": nid() if callable(nid) else None,
            "scratch_path": scratch.path,
            "scratch_token": scratch.token_hex,
            "pid": os.getpid(),
            "actor_id": aid() if callable(aid) else None,
            "pvm_addr": int(self._pvm_probe.ctypes.data),
            "pvm_token": self._pvm_token.hex(),
        }

    def _ensure_scratch(self):
        with self._scratch_lock:
            if self.scratch is None:
                path = os.path.join(
                    _scratch_dir(),
                    f"rt_coll_{self.w.worker_id.hex()[:12]}_{os.getpid()}")
                self.scratch = ScratchArena(
                    path, max(1 << 20, cfg.collective_scratch_bytes))
            # Return under the lock: a concurrent close() nulls the
            # attribute, and callers must get the arena they created,
            # never None.
            return self.scratch

    def prepare_group(self, group: str, endpoints: dict[int, Endpoint],
                      infos: dict | None = None):
        """Probe each peer's same-host reachability: first one-sided
        reads (process_vm_readv of the peer's 16-byte probe token — a
        pid recycled on another host can never match), then the scratch
        arena file (token-stamped), else the wire."""
        self.forget_group(group)
        force_wire = cfg.collective_data_plane == "wire"
        for ep in endpoints.values():
            if force_wire:
                continue
            if cfg.collective_pvm_reads:
                ep.pvm = self._probe_pvm(ep, (infos or {}).get(ep.rank))
            ep.same_host = ep.pvm or self._probe_scratch(ep)

    def _probe_pvm(self, ep: Endpoint, info: dict | None) -> bool:
        tok = (info or {}).get("pvm_token")
        if not tok or not ep.pvm_addr or not ep.pid:
            return False
        try:
            got = np.empty(_TOKEN_LEN, np.uint8)
            pvm_read_into(ep.pid, ep.pvm_addr, got.ctypes.data,
                          _TOKEN_LEN)
            return got.tobytes() == bytes.fromhex(tok)
        except OSError:
            return False

    def _probe_scratch(self, ep: Endpoint) -> bool:
        if not ep.scratch_path or not ep.scratch_token:
            return False
        try:
            with open(ep.scratch_path, "rb") as f:
                return f.read(_TOKEN_LEN) == bytes.fromhex(ep.scratch_token)
        except OSError:
            return False

    def peer_view(self, path: str, token_hex: str, off: int,
                  n: int) -> memoryview:
        ps = self._peer_maps.get(path)
        if ps is None:
            ps = self._peer_maps[path] = _PeerScratch(path, token_hex)
        return ps.view[off:off + n]

    # ----------------------------------------------------------- send side
    def send(self, ep: Endpoint, key, arr, deadline, slot=None):
        """Queue one chunk for ``ep``; returns a concurrent future that
        resolves once the receiver consumed it (slot ack)."""
        return self.multicast([(ep, key)], arr, deadline, slot=slot)[0]

    def _sticky_slot(self, group: str, slot: str, n: int, deadline) -> int:
        """Stable arena offset for one (group, stream) send slot; grows
        (power-of-two classes) when an op outsizes the current slot."""
        scratch = self._ensure_scratch()
        key = (group, slot)
        cur = self._sticky.get(key)
        if cur is not None and cur[1] >= n:
            return cur[0]
        want = max(_ALIGN, 1 << (max(1, n) - 1).bit_length())
        off = scratch.alloc(want, deadline)
        if cur is not None:
            scratch.free(cur[0], cur[1])
        self._sticky[key] = (off, want)
        return off

    def multicast(self, targets, arr, deadline, slot: str | None = None):
        """Send one buffer to many peers.  pvm-capable peers get a tiny
        descriptor naming (pid, address, len) and read the buffer out
        of THIS process themselves — zero sender-side bytes moved.
        Scratch-only peers share ONE arena region (written once; with a
        ``slot`` stream tag it is sticky across ops so its pages stay
        warm).  Wire peers each get a windowed raw-frame stream of the
        same memoryview.  Returns one concurrent future per target; the
        source buffer must stay alive and unmutated until they resolve
        (one-sided readers read it in place)."""
        arr_c = np.ascontiguousarray(arr)
        mv = memoryview(arr_c).cast("B")
        loop = self.w.loop
        futs = []
        pvm = [(ep, key) for ep, key in targets if ep.pvm]
        shm = [(ep, key) for ep, key in targets
               if ep.same_host and not ep.pvm]
        wire = [(ep, key) for ep, key in targets if not ep.same_host]
        for ep, key in pvm:
            hdr = {"op": "pvm", "k": key, "pid": os.getpid(),
                   "addr": int(arr_c.ctypes.data), "n": len(mv)}
            futs.append(asyncio.run_coroutine_threadsafe(
                self._ctl_send(ep, key, hdr, deadline, keep=arr_c),
                loop))
        if shm:
            scratch = self._ensure_scratch()
            n = len(mv)
            _slot_done = None
            if slot is not None:
                off = self._sticky_slot(shm[0][1][0], slot, n, deadline)
            else:
                off = scratch.alloc(n, deadline)
                remaining = [len(shm)]
                rlock = threading.Lock()

                def _slot_done(_f):
                    with rlock:
                        remaining[0] -= 1
                        last = remaining[0] == 0
                    if last:
                        scratch.free(off, n)

            scratch.write(off, mv)  # op-thread memcpy, loop untouched
            for ep, key in shm:
                hdr = {"op": "shm", "k": key, "path": scratch.path,
                       "tok": scratch.token_hex, "off": off, "n": n}
                f = asyncio.run_coroutine_threadsafe(
                    self._ctl_send(ep, key, hdr, deadline), loop)
                if _slot_done is not None:
                    f.add_done_callback(_slot_done)
                futs.append(f)
        for ep, key in wire:
            futs.append(asyncio.run_coroutine_threadsafe(
                self._wire_send(ep, key, mv, deadline), loop))
        return futs

    async def _fp(self, ep: Endpoint, group):
        if failpoints.ACTIVE:
            act = failpoints.check("collective.chunk", peer=f"r{ep.rank}")
            if act is not None:
                if act.kind == "error":
                    raise CollectiveGroupError(
                        group, "failpoint: injected collective chunk "
                        f"error to rank {ep.rank}")
                if act.kind == "delay":
                    await asyncio.sleep(act.delay_s)
                elif act.kind == "drop":
                    return True  # chunk vanishes; receiver times out
                elif act.kind == "kill":
                    os._exit(int(act.arg or 1))
        return False

    async def _conn(self, ep: Endpoint):
        return await self.w._worker_conn(tuple(ep.addr))

    async def _ctl_send(self, ep: Endpoint, key, hdr, deadline,
                        keep=None):
        # ``keep`` pins the source buffer for one-sided readers: the
        # peer reads our memory until the reply arrives.
        group = key[0]
        try:
            if await self._fp(ep, group):
                return
            conn = await self._conn(ep)
            rep = await conn.request("coll_ctl", hdr,
                                     timeout=_remain(deadline))
        except CollectiveGroupError:
            raise
        except (protocol.RpcError, ConnectionError, OSError) as e:
            raise CollectiveGroupError(
                group, f"lost rank {ep.rank} mid-op: "
                f"{type(e).__name__}: {e}") from e
        except asyncio.TimeoutError as e:
            raise CollectiveGroupError(
                group, f"timed out waiting for rank {ep.rank} to consume "
                f"chunk {key}") from e
        self._check_rep(group, ep, rep)

    async def _wire_send(self, ep: Endpoint, key, mv, deadline):
        group = key[0]
        n = len(mv)
        csz = max(1, cfg.collective_chunk_bytes)
        try:
            if await self._fp(ep, group):
                return
            conn = await self._conn(ep)
            if n <= csz:
                rep = await conn.blob_request(
                    "coll_chunk", {"k": key, "o": 0, "n": n, "t": n}, mv,
                    timeout=_remain(deadline))
                self._check_rep(group, ep, rep)
                return

            async def _sub(o, ln):
                rep = await conn.blob_request(
                    "coll_chunk", {"k": key, "o": o, "n": ln, "t": n},
                    mv[o:o + ln], timeout=_remain(deadline))
                self._check_rep(group, ep, rep)

            await transfer.run_windowed(
                (lambda o=o, ln=min(csz, n - o): _sub(o, ln)
                 for o in range(0, n, csz)),
                cfg.transfer_window_chunks)
        except CollectiveGroupError:
            raise
        except (protocol.RpcError, ConnectionError, OSError) as e:
            raise CollectiveGroupError(
                group, f"lost rank {ep.rank} mid-op: "
                f"{type(e).__name__}: {e}") from e
        except asyncio.TimeoutError as e:
            raise CollectiveGroupError(
                group, f"timed out sending chunk {key} to "
                f"rank {ep.rank}") from e

    def _check_rep(self, group, ep, rep):
        if isinstance(rep, dict) and rep.get("error"):
            raise CollectiveGroupError(
                group, f"rank {ep.rank} refused chunk: {rep['error']}")

    # ----------------------------------------------------------- recv side
    def recv(self, ep: Endpoint, key, nbytes: int, deadline,
             sink: np.ndarray | None = None) -> RecvHandle:
        """Post a chunk receive.  ``sink`` (a writable C-contiguous
        array) lets wire-path bytes land directly in the destination
        tensor when the receive wins the registration race."""
        sink_mv = None
        if sink is not None and not ep.same_host:
            sink_mv = memoryview(sink).cast("B")
        cfut = asyncio.run_coroutine_threadsafe(
            self._recv_async(key, nbytes, sink_mv, deadline), self.w.loop)
        return RecvHandle(self, key, nbytes, deadline, cfut, sink)

    async def _recv_async(self, key, nbytes, sink_mv, deadline):
        group = key[0]
        reason = self._aborted.get(group)
        if reason is not None:
            raise CollectiveGroupError(group, reason)
        entry = self._entries.get(key)
        if entry is None:
            entry = self._entries[key] = _new_entry(group)
        if entry["payload"] is not None:
            self._entries.pop(key, None)
            return entry["payload"]
        entry["sink"] = sink_mv
        fut = entry["fut"] = asyncio.get_running_loop().create_future()
        try:
            remain = _remain(deadline)
            if remain is None:
                return await fut
            return await asyncio.wait_for(fut, remain)
        except asyncio.TimeoutError as e:
            raise CollectiveGroupError(
                group, f"timed out waiting for chunk {key} "
                f"({entry['got']} of {nbytes} bytes arrived)") from e
        finally:
            cur = self._entries.get(key)
            if cur is entry:
                self._entries.pop(key, None)

    def _deliver(self, key, payload):
        """Complete (or stash) one fully-arrived chunk."""
        entry = self._entries.get(key)
        if entry is not None and entry["fut"] is not None \
                and not entry["fut"].done():
            self._entries.pop(key, None)
            entry["fut"].set_result(payload)
        else:
            if entry is None:
                entry = self._entries[key] = _new_entry(key[0])
            entry["payload"] = payload

    # -------------------------------------------------------- rpc handlers
    def _blob_sink(self, conn, header, nraw):
        """Blob provider for coll_chunk: land the raw body straight in
        the posted receive's sink.  First arrival fixes the delivery
        mode — a chunk that beat its recv registration stays on the
        staged-bytes path for all its sub-chunks."""
        try:
            key = tuple(header["k"])
            o, t = header["o"], header["t"]
        except Exception:
            return None
        if key[0] in self._aborted:
            return None
        entry = self._entries.get(key)
        if entry is None:
            entry = self._entries[key] = _new_entry(key[0])
        if entry["via"] is None:
            entry["via"] = ("sink" if entry["sink"] is not None
                            and t <= len(entry["sink"]) else "buf")
        if entry["via"] == "sink" and o + nraw <= len(entry["sink"]):
            return entry["sink"][o:o + nraw]
        return None

    async def _rpc_chunk(self, conn, frame):
        hdr = frame.header
        key = tuple(hdr["k"])
        group = key[0]
        o, n, t = hdr["o"], hdr["n"], hdr["t"]
        reason = self._aborted.get(group)
        if reason is not None:
            return {"error": f"group aborted: {reason}"}
        entry = self._entries.get(key)
        if entry is None:
            entry = self._entries[key] = _new_entry(group)
        if frame.data is not None:
            if entry["via"] is None:
                entry["via"] = "buf"
            if entry["buf"] is None:
                entry["buf"] = bytearray(t)
            entry["buf"][o:o + n] = frame.data
        entry["got"] += n
        if entry["got"] >= t:
            if entry["via"] == "sink":
                self._deliver(key, ("sink",))
            else:
                self._deliver(key, ("bytes",
                                    entry["buf"] if entry["buf"] is not None
                                    else b""))
        return {"ok": 1}

    async def _rpc_ctl(self, conn, body):
        op = body.get("op")
        if op == "abort":
            self._abort_group(body.get("group", "?"),
                              body.get("reason", "group aborted"))
            return {"ok": 1}
        if op == "ping":
            return {"ok": 1}
        if op not in ("shm", "pvm"):
            return {"error": f"unknown coll_ctl op {op!r}"}
        key = tuple(body["k"])
        group = key[0]
        reason = self._aborted.get(group)
        if reason is not None:
            return {"error": f"group aborted: {reason}"}
        evt = asyncio.Event()
        if op == "pvm":
            self._deliver(key, ("pvm", body["pid"], body["addr"],
                                body["n"], None, evt))
        else:
            self._deliver(key, ("shm", body["path"], body["tok"],
                                body["off"], body["n"], evt))
        try:
            await asyncio.wait_for(evt.wait(),
                                   max(1.0, cfg.collective_timeout_s))
        except asyncio.TimeoutError:
            return {"error": f"receiver never consumed shm chunk {key}"}
        reason = self._aborted.get(group)
        if reason is not None:
            return {"error": f"group aborted: {reason}"}
        return {"ok": 1}

    def signal_done(self, evt: asyncio.Event):
        self.w.loop.call_soon_threadsafe(evt.set)

    # ----------------------------------------------------------- lifecycle
    def abort_group(self, group: str, reason: str):
        """Thread-safe entry point (coordinator death watch, destroy)."""
        self.w.loop.call_soon_threadsafe(self._abort_group, group, reason)

    def _abort_group(self, group: str, reason: str):
        if group in self._aborted:
            return
        self._aborted[group] = reason
        while len(self._aborted) > _MAX_ABORT_MARKS:
            self._aborted.popitem(last=False)
        err = CollectiveGroupError(group, reason)
        for key in [k for k in self._entries if k[0] == group]:
            entry = self._entries.pop(key)
            fut = entry.get("fut")
            if fut is not None and not fut.done():
                fut.set_exception(err)
            payload = entry.get("payload")
            if payload is not None and payload[0] in ("shm", "pvm"):
                payload[5].set()  # unblock the parked ctl handler

    def forget_group(self, group: str):
        """Clear abort marks/state and release the group's sticky
        scratch slots so a destroyed group's name can be reused."""
        with self._scratch_lock:
            scratch = self.scratch
        for key in [k for k in self._sticky if k[0] == group]:
            off, sz = self._sticky.pop(key)
            if scratch is not None:
                scratch.free(off, sz)

        def _clear():
            self._aborted.pop(group, None)
            for key in [k for k in self._entries if k[0] == group]:
                self._entries.pop(key, None)
        if self.w.loop is not None:
            self.w.loop.call_soon_threadsafe(_clear)

    def close(self):
        for ps in self._peer_maps.values():
            ps.close()
        self._peer_maps.clear()
        # Detach under the same lock _ensure_scratch publishes under:
        # a bare write here could hand a concurrent _ensure_scratch an
        # arena that close() is about to unmap (RTC101).
        with self._scratch_lock:
            scratch, self.scratch = self.scratch, None
        if scratch is not None:
            scratch.close()


def get_transport() -> CollectiveTransport:
    from ray_tpu._private import worker as worker_mod
    w = worker_mod.global_worker
    if w is None or not w.connected or w.loop is None:
        raise RuntimeError(
            "collective transport requires a connected ray_tpu worker "
            "(call ray_tpu.init first)")
    if w._collective_transport is None:
        w._collective_transport = CollectiveTransport(w)
    return w._collective_transport
