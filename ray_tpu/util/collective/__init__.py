from ray_tpu.util.collective.collective import (  # noqa: F401
    CollectiveMixin,
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_group_handle,
    init_collective_group,
    recv,
    reducescatter,
    send,
)
from ray_tpu.util.collective.types import ReduceOp  # noqa: F401
