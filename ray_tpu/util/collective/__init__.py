"""Host-side collectives on the transfer plane.

Rendezvous, op sequencing, barriers, and small tensors go through a
named **coordinator actor** per group; bulk tensors move **peer to
peer** — same-host members exchange chunks through token-stamped
/dev/shm scratch arenas (one memcpy each side), cross-host members
through raw ``KIND_BLOB`` frames with the transfer plane's sliding
window.  Round ids are coordinator-issued, so a desynced member raises
a structured :class:`CollectiveGroupError` at the exact diverging round
instead of deadlocking; a member death or ``destroy_collective_group``
mid-op fails every blocked peer fast the same way.  ``fuse_buckets`` /
``allreduce_async`` give DDP-style bucket fusion with
compute/communication overlap.  Knobs: ``RT_COLLECTIVE_TIMEOUT_S``,
``RT_COLLECTIVE_FASTPATH_MIN_BYTES``, ``RT_COLLECTIVE_DATA_PLANE``
(auto|wire|store|coord), ``RT_COLLECTIVE_CHUNK_BYTES``,
``RT_COLLECTIVE_SCRATCH_BYTES``, ``RT_COLLECTIVE_BUCKET_BYTES`` — see
README "Collectives on the transfer plane"."""

from ray_tpu.util.collective.collective import (  # noqa: F401
    CollectiveBucket,
    CollectiveMixin,
    CollectiveWork,
    abort_collective_group,
    allgather,
    allreduce,
    allreduce_async,
    allreduce_coalesced,
    barrier,
    broadcast,
    create_collective_gang,
    create_collective_group,
    destroy_collective_group,
    destroy_local_member,
    ensure_coordinator,
    fuse_buckets,
    get_group_handle,
    init_collective_group,
    recv,
    reducescatter,
    send,
)
from ray_tpu.util.collective.types import (  # noqa: F401
    CollectiveGroupError,
    ReduceOp,
)
