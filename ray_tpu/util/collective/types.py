"""Collective types (reference: python/ray/util/collective/types.py)."""

from __future__ import annotations

import enum


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "prod"
    MIN = "min"
    MAX = "max"


class Backend:
    # In-slice tensor collectives compile to XLA collectives over ICI inside
    # jit/shard_map — they are not routed through this actor-plane backend.
    # This backend ("tcp") is the CPU/control-plane equivalent of the
    # reference's gloo path; "xla" marks in-graph use.
    TCP = "tcp"
    XLA = "xla"
    NIL = "nil"
