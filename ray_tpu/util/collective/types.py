"""Collective types (reference: python/ray/util/collective/types.py)."""

from __future__ import annotations

import enum


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "prod"
    MIN = "min"
    MAX = "max"


class Backend:
    # In-slice tensor collectives compile to XLA collectives over ICI inside
    # jit/shard_map — they are not routed through this actor-plane backend.
    # This backend ("tcp") is the CPU/control-plane equivalent of the
    # reference's gloo path; "xla" marks in-graph use.
    TCP = "tcp"
    XLA = "xla"
    NIL = "nil"


class CollectiveGroupError(RuntimeError):
    """A collective group op cannot complete: a member died, the group
    was destroyed mid-op, the members desynchronized (op mismatch at a
    round), or the data plane lost a peer.  Structured so gang
    schedulers can tell a broken GANG (restartable) from a user error:
    ``group`` names the group, ``reason`` says what broke it."""

    def __init__(self, group: str = "?", reason: str = ""):
        self.group = group
        self.reason = reason
        super().__init__(f"collective group '{group}': {reason}")

    def __reduce__(self):
        return (CollectiveGroupError, (self.group, self.reason))
