"""Collective communication across actors/tasks.

TPU-native re-design of the reference collective layer (reference:
python/ray/util/collective/collective.py — init_collective_group :120,
allreduce :258, barrier :298, broadcast :373, allgather :423,
reducescatter :472, send/recv :531/:594; NCCL backend via cupy in
collective_group/nccl_collective_group.py:127, gloo via pygloo).

On TPU the *tensor* plane never goes through host collectives: gradient
allreduce etc. compile to XLA collectives over ICI inside jit/pjit (see
ray_tpu.parallel).  What remains for the framework plane — rendezvous,
barriers, CPU-side state sync (RL rollout weights, GBDT histograms,
data-parallel host gradients) — is served here with the same split the
reference's NCCL group uses: a **coordinator actor** per group for
rendezvous, barriers, op sequencing, and small-tensor reductions, and a
**peer-to-peer data plane** (transport.py) that moves bulk tensors
member-to-member as raw blob frames / same-host scratch memcpys.

Design points (see README "Collectives on the transfer plane"):

* **Coordinator-issued rounds.** Every synchronized op consumes one
  server-side per-rank op index at the coordinator; the round's mode is
  fixed by the first arrival and any member presenting a different op
  at the same index fails the WHOLE group with a structured
  :class:`CollectiveGroupError` (op mismatch) instead of deadlocking on
  desynced client-side counters.
* **Direct chunked exchange, rank-order fold.** Large allreduce =
  reduce-scatter (every pair exchanges its chunk concurrently — the
  same 2·(W−1)/W per-member bytes as a ring, without W−1 serialized
  latency steps) + direct allgather.  Contributions are folded in rank
  order, which makes the result BIT-IDENTICAL to the coordinator's
  left-fold reduction — the parity contract train/gbdt.py relies on.
* **Bucket fusion + async handles.** ``fuse_buckets`` coalesces many
  small tensors into flat buffers that ride one rendezvous;
  ``allreduce_async`` returns a :class:`CollectiveWork` handle so
  communication overlaps compute on the caller's thread.
* **Gang failure semantics.** The coordinator watches member actors
  through the GCS actor-event channel and aborts every pending round
  AND pushes abort frames at member data planes when one dies; a
  destroyed group fails blocked peers the same way.  Waits are bounded
  by ``cfg.collective_timeout_s`` (RT_COLLECTIVE_TIMEOUT_S) everywhere.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

import ray_tpu
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu.util.collective.types import CollectiveGroupError, ReduceOp

logger = logging.getLogger(__name__)

_groups: dict[str, "GroupMember"] = {}

_COORD_PREFIX = "_rt_collective_coord::"

_UNSET = object()

# Back-compat alias (pre-rewrite name): tensors at/above this size leave
# the coordinator and ride the peer-to-peer data plane.
RING_THRESHOLD_BYTES = cfg.collective_fastpath_min_bytes


def _reduce(arrays, op: ReduceOp):
    out = np.array(arrays[0], copy=True)
    for a in arrays[1:]:
        if op == ReduceOp.SUM:
            out = out + a
        elif op == ReduceOp.PRODUCT:
            out = out * a
        elif op == ReduceOp.MIN:
            out = np.minimum(out, a)
        elif op == ReduceOp.MAX:
            out = np.maximum(out, a)
    return out


def _reduce_into(acc, contrib, op: ReduceOp):
    """One fold step, elementwise-identical to _reduce's fold (same
    ufuncs, same order) so the data-plane result is bit-identical to
    the coordinator path."""
    if op == ReduceOp.SUM:
        np.add(acc, contrib, out=acc)
    elif op == ReduceOp.PRODUCT:
        np.multiply(acc, contrib, out=acc)
    elif op == ReduceOp.MIN:
        np.minimum(acc, contrib, out=acc)
    else:
        np.maximum(acc, contrib, out=acc)


class _Coordinator:
    """Async actor: rendezvous, op sequencing, barriers, small-tensor
    reductions, and the group's failure authority.  One per collective
    group, named, owned by whichever member created it first.

    Round ids are SERVER-ISSUED: each ``collect`` consumes the calling
    rank's next op index, so a member that slips an extra op in no
    longer silently desyncs every later round — the mismatch surfaces
    as a CollectiveGroupError at the exact round where the sequences
    diverged."""

    def __init__(self, world_size: int, group_name: str = "default"):
        import asyncio
        self.world_size = world_size
        self.group_name = group_name
        self._next_op: dict = {}      # rank -> next op index
        self._rounds: dict = {}       # op index -> round state
        self._cond = asyncio.Condition()
        self._mail: dict = {}
        self._mail_cond = asyncio.Condition()
        self._seq = 0                 # data-plane rendezvous sequence
        self._members: dict = {}      # rank -> endpoint info
        self._member_actors: dict = {}  # actor_id hex -> rank
        self._reg_cond = asyncio.Condition()
        self._dead: str | None = None
        self._watch_started = False

    def _err(self) -> CollectiveGroupError:
        return CollectiveGroupError(self.group_name, self._dead or "dead")

    async def collect(self, mode, rank, data):
        import asyncio
        async with self._cond:
            if self._dead is not None:
                raise self._err()
            idx = self._next_op.get(rank, 0)
            self._next_op[rank] = idx + 1
            rnd = self._rounds.get(idx)
            if rnd is None:
                rnd = self._rounds[idx] = {"mode": mode, "data": {},
                                           "result": _UNSET, "reads": set()}
                if mode.startswith("rdv:"):
                    self._seq += 1
                    rnd["seq"] = self._seq
            if mode != rnd["mode"]:
                # The group's op sequences diverged: fail EVERYONE now
                # (the old client-counter scheme deadlocked here).
                self._dead = (
                    f"op mismatch at round {idx}: rank {rank} called "
                    f"{mode!r} but the round opened as {rnd['mode']!r} "
                    "— members issued different op sequences")
                self._cond.notify_all()
                asyncio.get_running_loop().create_task(
                    self._after_death())
                raise self._err()
            rnd["data"][rank] = data
            self._cond.notify_all()
            while (self._dead is None and rnd["result"] is _UNSET
                   and len(rnd["data"]) < self.world_size):
                await self._cond.wait()
            if self._dead is not None and rnd["result"] is _UNSET:
                raise self._err()
            if rnd["result"] is _UNSET:
                full = rnd["data"]
                if mode.startswith("rdv:"):
                    # Data-plane rendezvous: the round doubles as a
                    # descriptor exchange (tiny per-rank payloads, e.g.
                    # one-sided read addresses) so a whole bulk phase
                    # needs no further coordination.
                    result = {"seq": rnd["seq"],
                              "gathered": dict(full)}
                elif mode.startswith("reduce:"):
                    op = ReduceOp(mode.split(":", 2)[1])
                    result = _reduce([full[r] for r in sorted(full)], op)
                elif mode == "gather":
                    result = [full[r] for r in sorted(full)]
                elif mode.startswith("src:"):
                    result = full[int(mode.split(":", 1)[1])]
                else:
                    result = True
                rnd["result"] = result
            rnd["reads"].add(rank)
            result = rnd["result"]
            # Last reader cleans the round up.
            if len(rnd["reads"]) == self.world_size:
                self._rounds.pop(idx, None)
            return result

    async def put_mail(self, tag, data):
        async with self._mail_cond:
            if self._dead is not None:
                raise self._err()
            self._mail.setdefault(tag, deque()).append(data)
            self._mail_cond.notify_all()
        return True

    async def get_mail(self, tag):
        async with self._mail_cond:
            while True:
                if self._dead is not None:
                    raise self._err()
                q = self._mail.get(tag)
                if q:
                    item = q.popleft()
                    # Tags are single-use and globally unique: drop
                    # drained queues or a long run leaks millions.
                    if not q:
                        self._mail.pop(tag, None)
                    return item
                await self._mail_cond.wait()

    async def register(self, rank, info):
        """Data-plane rendezvous: blocks until every member published
        its endpoint, returns the full table.  Also arms the actor
        death watch for self-registered members."""
        self._start_watch()
        async with self._reg_cond:
            if self._dead is not None:
                raise self._err()
            self._members[rank] = info
            aid = info.get("actor_id")
            if aid:
                self._member_actors[aid] = rank
            self._reg_cond.notify_all()
            while self._dead is None \
                    and len(self._members) < self.world_size:
                await self._reg_cond.wait()
            if self._dead is not None:
                raise self._err()
            return dict(self._members)

    async def watch(self, actor_ranks: dict):
        """Arm the death watch for the given {actor_id hex: rank}
        mapping (called by create_collective_group from the driver, so
        gang death is detected even before first data-plane use)."""
        self._member_actors.update(actor_ranks)
        self._start_watch()
        return True

    async def abort(self, reason: str = "group destroyed"):
        """Fail every pending and future group op NOW (destroy while
        ops are in flight, member death)."""
        await self._die(reason or "group destroyed")
        return True

    async def _die(self, reason: str):
        if self._dead is not None:
            return
        self._dead = reason
        await self._after_death()

    async def _after_death(self):
        async with self._cond:
            self._cond.notify_all()
        async with self._mail_cond:
            self._mail_cond.notify_all()
        async with self._reg_cond:
            self._reg_cond.notify_all()
        await self._push_aborts(self._dead or "dead")

    async def _push_aborts(self, reason: str):
        """Best-effort abort frames at every registered member's data
        plane so a member blocked on a peer CHUNK (not on us) also
        fails fast instead of riding out the full timeout."""
        from ray_tpu._private import worker as worker_mod
        w = worker_mod.global_worker
        if w is None:
            return
        for _rank, info in list(self._members.items()):
            try:
                conn = await w._worker_conn(tuple(info["addr"]))
                await conn.push("coll_ctl", {
                    "op": "abort", "group": self.group_name,
                    "reason": reason})
            except Exception:
                pass

    def _start_watch(self):
        """Subscribe (once) to GCS actor events through the hosting
        CoreWorker; a DEAD/RESTARTING member actor kills the group."""
        if self._watch_started:
            return
        self._watch_started = True
        import asyncio
        try:
            from ray_tpu._private import worker as worker_mod
            w = worker_mod.global_worker
            if w is None or w.gcs is None:
                return
        except Exception:
            return

        def _on_actor_event(msg):
            try:
                if not msg or msg.get("event") not in ("dead",
                                                       "restarting"):
                    return
                actor = msg.get("actor") or {}
                aid = actor.get("actor_id")
                aid = aid.hex() if hasattr(aid, "hex") else aid
                rank = self._member_actors.get(aid)
                if rank is None:
                    return
                cause = actor.get("death_cause") or msg["event"]
                reason = (f"member rank {rank} (actor "
                          f"{str(aid)[:12]}) {msg['event']}: {cause}")
                asyncio.get_running_loop().create_task(self._die(reason))
            except Exception:
                logger.exception("collective death watch handler failed")

        w._pubsub_handlers["actors"] = _on_actor_event
        t = asyncio.get_running_loop().create_task(
            w.gcs.request("subscribe", {"channels": ["actors"]}))
        t.add_done_callback(lambda t: t.cancelled() or t.exception())


class GroupMember:
    def __init__(self, group_name: str, world_size: int, rank: int):
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._plane = None  # (transport, {rank: Endpoint}) after rendezvous
        self._executor: ThreadPoolExecutor | None = None
        self._exec_lock = threading.Lock()
        # Reusable op-local work buffers (accumulators, wire staging),
        # keyed by stream tag.  First-touch page faults on fresh memory
        # are expensive under hardened kernels; steady-state gradient
        # sync must run fault-free, so work buffers are warm and
        # recycled (ops within a group are serialized by run_op).
        self._bufs: dict = {}
        coord_name = _COORD_PREFIX + group_name
        try:
            self.coord = ray_tpu.get_actor(coord_name)
        except ValueError:
            try:
                coord_cls = ray_tpu.remote(_Coordinator)
                self.coord = coord_cls.options(
                    name=coord_name, num_cpus=0).remote(world_size,
                                                        group_name)
            except ValueError:
                self.coord = ray_tpu.get_actor(coord_name)
        # Eagerly attach the data-plane transport (registers the
        # coll_ctl/coll_chunk handlers, clears any stale abort mark
        # from an earlier group of the same name).
        try:
            from ray_tpu.util.collective import transport as _tp
            _tp.get_transport().forget_group(group_name)
        except Exception:
            pass

    def _timeout(self) -> float:
        return max(0.1, cfg.collective_timeout_s)

    def _coord_get(self, ref):
        try:
            return ray_tpu.get(ref, timeout=self._timeout())
        except CollectiveGroupError:
            raise
        except Exception as e:
            if isinstance(e, CollectiveGroupError):
                raise
            raise CollectiveGroupError(
                self.group_name,
                f"coordinator call failed: {type(e).__name__}: {e}") from e

    def collect(self, mode, value):
        return self._coord_get(
            self.coord.collect.remote(mode, self.rank, value))

    def put_mail(self, tag, data, timeout=None):
        self._coord_get(self.coord.put_mail.remote(tag, data))

    def get_mail(self, tag, timeout=None):
        return self._coord_get(self.coord.get_mail.remote(tag))

    def run_op(self, fn, op_name: str | None = None,
               nbytes: int | None = None):
        """Submit a synchronized group op to this member's serial op
        executor.  ALL round-consuming ops ride it, so the member's op
        order (and thus its coordinator op indexes) is its submission
        order even when sync and async ops interleave.

        ``op_name`` makes the op a collective.<op_name> span in the
        trace ring, linked under the SUBMITTER's span context (captured
        here — the op executor is a different thread, contextvars don't
        cross it) — so comm shows up against compute in the timeline
        and the phase sub-spans (rendezvous/bulk/fold) nest under it."""
        if op_name is not None:
            ctx = _tracing.current()
            inner = fn
            group, world, rank = self.group_name, self.world_size, \
                self.rank

            def fn():  # noqa: F811 — traced wrapper of the op body
                token = _tracing.set_current(*ctx) if ctx else None
                try:
                    with _tracing.span(
                            "collective", f"collective.{op_name}",
                            args={"group": group, "world": world,
                                  "rank": rank,
                                  "bytes": nbytes or 0}) as h:
                        out = inner()
                        h.args.setdefault(
                            "plane", _plane_for(self, nbytes or 0))
                        return out
                finally:
                    if token is not None:
                        _tracing.reset_current(token)
        with self._exec_lock:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    1, thread_name_prefix=f"coll-{self.group_name}")
            return self._executor.submit(fn)

    def fast_plane(self):
        """Rendezvous the data plane once: publish this member's
        endpoint, collect everyone's, probe same-host reachability.
        Returns (transport, {rank: Endpoint}) or None when this process
        cannot host the transport."""
        if self._plane is False:
            return None
        if self._plane is None:
            tr = None
            try:
                from ray_tpu.util.collective import transport as _tp
                tr = _tp.get_transport()
                info = tr.endpoint_info(self.rank)
            except Exception as e:
                logger.warning(
                    "collective data plane unavailable (%s); group '%s' "
                    "falls back to the coordinator", e, self.group_name)
                # STILL register (with a no-plane marker): the fallback
                # must be a GROUP decision — peers blocked in register
                # while we silently took the coordinator path would
                # hang to the full timeout.
                info = {"rank": self.rank, "no_plane": True}
            table = self._coord_get(
                self.coord.register.remote(self.rank, info))
            infos = {int(r): i for r, i in table.items()}
            if tr is None or any(i.get("no_plane")
                                 for i in infos.values()):
                self._plane = False
                return None
            from ray_tpu.util.collective.transport import Endpoint
            eps = {r: Endpoint(i) for r, i in infos.items()}
            eps.pop(self.rank, None)
            tr.prepare_group(self.group_name, eps, infos)
            self._plane = (tr, eps)
        return self._plane

    def buf(self, tag: str, size: int, dtype) -> np.ndarray:
        """Warm reusable work buffer for one op-local stream."""
        b = self._bufs.get(tag)
        if b is None or b.size < size or b.dtype != dtype:
            b = self._bufs[tag] = np.empty(size, dtype)
        return b[:size]

    def shutdown(self):
        if self._executor is not None:
            self._executor.shutdown(wait=False)
        self._bufs.clear()


def init_collective_group(world_size: int, rank: int,
                          backend: str = "tcp",
                          group_name: str = "default") -> None:
    """Join this process to a named collective group (reference:
    collective.py:120)."""
    if group_name in _groups:
        raise RuntimeError(f"already in collective group '{group_name}'")
    _groups[group_name] = GroupMember(group_name, world_size, rank)


def create_collective_group(actors, world_size: int, ranks: list[int],
                            backend: str = "tcp",
                            group_name: str = "default"):
    """Declare a group across actor handles from the driver (reference:
    collective.py declare_collective_group): calls init on each member
    and arms the coordinator's death watch with their actor ids, so a
    member dying mid-op fails the group fast instead of hanging peers
    to the collective timeout."""
    if len(actors) != len(ranks):
        raise ValueError(
            f"{len(actors)} actors but {len(ranks)} ranks")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(
            f"ranks {ranks} must be a permutation of 0..{world_size - 1}")
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(actor._rt_init_collective.remote(
            world_size, rank, backend, group_name))
    ray_tpu.get(refs, timeout=max(0.1, cfg.collective_timeout_s))
    mapping = {}
    for actor, rank in zip(actors, ranks):
        aid = getattr(actor, "_actor_id", None)
        if aid is not None:
            mapping[aid.hex()] = rank
    if mapping:
        try:
            coord = ray_tpu.get_actor(_COORD_PREFIX + group_name)
            ray_tpu.get(coord.watch.remote(mapping), timeout=60)
        except Exception:
            logger.warning("could not arm death watch for group '%s'",
                           group_name, exc_info=True)


def create_collective_gang(actor_cls, world_size: int, *,
                           group_name: str = "default",
                           strategy: str = "PACK",
                           actor_options: dict | None = None,
                           actor_args: tuple = (),
                           actor_kwargs: dict | None = None):
    """Gang-schedule a collective group: reserve a placement group with
    one bundle per rank, create the member actors inside it (bundle i =
    rank i), and wire them into ``group_name`` with the death watch
    armed.  Returns ``(actors, placement_group)``; the caller owns both
    (``destroy_collective_group`` + ``remove_placement_group``)."""
    from ray_tpu.util.placement_group import placement_group
    opts = dict(actor_options or {})
    # Bundles must mirror EVERY requested resource: a bundle-pinned
    # actor draws from its bundle's own pool, so a CPU-only bundle
    # would leave GPU/TPU/custom-resource members pending forever.
    bundle = {"CPU": opts.get("num_cpus", 1)}
    if opts.get("num_gpus"):
        bundle["GPU"] = opts["num_gpus"]
    if opts.get("num_tpus"):
        bundle["TPU"] = opts["num_tpus"]
    bundle.update(opts.get("resources") or {})
    bundles = [dict(bundle) for _ in range(world_size)]
    pg = placement_group(bundles, strategy=strategy)
    if not pg.wait(min(120.0, max(1.0, cfg.collective_timeout_s))):
        raise CollectiveGroupError(group_name,
                                   "gang placement group never became "
                                   f"ready ({world_size} bundles)")
    actors = []
    for rank in range(world_size):
        o = dict(opts)
        o["placement_group"] = pg
        o["placement_group_bundle_index"] = rank
        actors.append(actor_cls.options(**o).remote(
            *actor_args, **(actor_kwargs or {})))
    create_collective_group(actors, world_size, list(range(world_size)),
                            group_name=group_name)
    return actors, pg


def destroy_collective_group(group_name: str = "default") -> None:
    """Tear down the group: pending ops on EVERY member fail fast with
    CollectiveGroupError naming the group (coordinator abort + data
    plane abort frames), then the coordinator actor dies so the name
    can be reused with a different world size."""
    g = _groups.pop(group_name, None)
    if g is not None:
        g.shutdown()
    try:
        from ray_tpu._private import worker as worker_mod
        w = worker_mod.global_worker
        if w is not None and w._collective_transport is not None:
            w._collective_transport.forget_group(group_name)
    except Exception:
        pass
    try:
        coord = ray_tpu.get_actor(_COORD_PREFIX + group_name)
    except Exception:
        return
    try:
        ray_tpu.get(coord.abort.remote("group destroyed"), timeout=30)
    except Exception:
        pass
    try:
        ray_tpu.kill(coord)
    except Exception:
        pass


def destroy_local_member(group_name: str = "default") -> None:
    """Tear down THIS process's membership of a group without touching
    the coordinator or the other members: pop the handle, shut down the
    serial op executor (in-flight bucket handles fail with the group's
    CollectiveGroupError instead of lingering), and clear the
    transport's per-group state.  The elastic-training rejoin path uses
    this — the group as a whole is already dead (death watch / abort),
    and each survivor only needs to drop its local half before joining
    the re-formed incarnation under a fresh name."""
    g = _groups.pop(group_name, None)
    if g is not None:
        g.shutdown()
    try:
        from ray_tpu._private import worker as worker_mod
        w = worker_mod.global_worker
        if w is not None and w._collective_transport is not None:
            w._collective_transport.forget_group(group_name)
    except Exception:
        pass


def ensure_coordinator(group_name: str, world_size: int):
    """Driver side: get-or-create the named coordinator actor for a
    group BEFORE its members self-register (init_collective_group on
    each member get-or-creates too; pre-creating lets the driver arm
    the death watch first, so a member dying mid-formation still fails
    the group fast).  Returns the coordinator handle."""
    name = _COORD_PREFIX + group_name
    try:
        return ray_tpu.get_actor(name)
    except ValueError:
        try:
            coord_cls = ray_tpu.remote(_Coordinator)
            return coord_cls.options(name=name, num_cpus=0).remote(
                world_size, group_name)
        except ValueError:
            return ray_tpu.get_actor(name)


def abort_collective_group(group_name: str = "default",
                           reason: str = "aborted") -> None:
    """Fail every pending and future op of a group NOW without killing
    the coordinator (members observe a structured CollectiveGroupError
    naming ``reason``).  The elastic resize path uses this to break
    survivors out of the step loop so they rendezvous the new world
    size."""
    try:
        coord = ray_tpu.get_actor(_COORD_PREFIX + group_name)
    except Exception:
        return
    try:
        ray_tpu.get(coord.abort.remote(reason), timeout=30)
    except Exception:
        pass


def get_group_handle(group_name: str = "default") -> GroupMember:
    g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(
            f"not a member of collective group '{group_name}'; call "
            f"init_collective_group first")
    return g


def _as_numpy(tensor):
    return np.asarray(tensor)


def _writeback(tensor, out):
    if isinstance(tensor, np.ndarray) and isinstance(out, np.ndarray) \
            and out.base is not None and np.shares_memory(tensor, out):
        return tensor  # in-place fast path already wrote the result
    try:
        tensor[...] = out
        return tensor
    except (TypeError, ValueError):
        # Non-writable tensor: never hand back a cached work buffer
        # (the next op would overwrite it under the caller).
        return np.array(out, copy=True) if isinstance(out, np.ndarray) \
            else out


def _plane_for(g: GroupMember, nbytes: int) -> str:
    """Pick the data plane for one op: "coord" (coordinator round
    trip), "store" (legacy object-store ring, kept as the bench
    baseline), or "fast" (peer-to-peer transfer plane)."""
    mode = cfg.collective_data_plane
    if g.world_size <= 1 or mode == "coord":
        return "coord"
    if nbytes < cfg.collective_fastpath_min_bytes:
        return "coord"
    if mode == "store":
        return "store"
    if g.fast_plane() is None:
        return "coord"
    return "fast"


def _chunk_slices(n: int, w: int) -> list[slice]:
    q, r = divmod(n, w)
    out, pos = [], 0
    for i in range(w):
        ln = q + (1 if i < r else 0)
        out.append(slice(pos, pos + ln))
        pos += ln
    return out


def _wait_sends(g: GroupMember, futs, deadline):
    for f in futs:
        remain = max(0.1, deadline - time.monotonic()) + 10.0
        try:
            f.result(remain)
        except CollectiveGroupError:
            raise
        except Exception as e:
            raise CollectiveGroupError(
                g.group_name,
                f"chunk send failed: {type(e).__name__}: {e}") from e


# --------------------------------------------------------------- allreduce
def allreduce(tensor, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM):
    """In-place allreduce of a host tensor across the group (reference:
    collective.py:258).  Device tensors are fetched to host; for
    on-device gradient reduction use XLA collectives via
    ray_tpu.parallel instead.

    Large tensors ride the peer-to-peer data plane (direct chunked
    reduce-scatter + allgather over same-host scratch memcpys / raw
    blob frames); the result is bit-identical to the coordinator path
    (rank-order fold)."""
    g = get_group_handle(group_name)
    arr = _as_numpy(tensor)
    out = g.run_op(lambda: _allreduce_impl(g, arr, op),
                   "allreduce", arr.nbytes).result(
        g._timeout() + 60)
    return _writeback(tensor, out)


def allreduce_async(tensor, group_name: str = "default",
                    op: ReduceOp = ReduceOp.SUM) -> "CollectiveWork":
    """Start an allreduce and return a :class:`CollectiveWork` handle;
    ``wait()`` writes the result back into ``tensor`` (when writable)
    and returns it.  Ops submitted to one group run in submission order
    on the member's op executor, so async and sync ops compose as long
    as every member submits the same sequence."""
    g = get_group_handle(group_name)
    arr = _as_numpy(tensor)
    fut = g.run_op(lambda: _allreduce_impl(g, arr, op),
                   "allreduce", arr.nbytes)
    return CollectiveWork(fut, g,
                          finalize=lambda out: _writeback(tensor, out))


def _allreduce_impl(g: GroupMember, arr: np.ndarray, op: ReduceOp):
    plane = _plane_for(g, arr.nbytes)
    if plane == "fast":
        return _fast_allreduce(g, arr, op)
    if plane == "store":
        return _store_ring_allreduce(g, arr, op)
    return g.collect(f"reduce:{op.value}", arr)


def _all_onesided(eps: dict) -> bool:
    return bool(eps) and all(ep.pvm for ep in eps.values())


def _pvm_fp(g: GroupMember, rank: int):
    """Failpoint hook for the one-sided read path (collective.chunk:
    error/kill against peer r<rank>)."""
    from ray_tpu._private import failpoints
    if failpoints.ACTIVE:
        act = failpoints.check("collective.chunk", peer=f"r{rank}")
        if act is not None:
            if act.kind == "error":
                raise CollectiveGroupError(
                    g.group_name, "failpoint: injected collective "
                    f"chunk error to rank {rank}")
            if act.kind == "delay":
                time.sleep(act.delay_s)
            elif act.kind == "kill":
                import os
                os._exit(int(act.arg or 1))


def _pvm_read(g: GroupMember, desc, dst: np.ndarray, off: int, n: int,
              rank: int):
    """One chunk straight out of a peer's address space into ``dst``."""
    from ray_tpu.util.collective import transport as _tp
    _pvm_fp(g, rank)
    try:
        _tp.pvm_read_into(desc["pid"], desc["addr"] + off,
                          dst.ctypes.data, n)
    except OSError as e:
        raise CollectiveGroupError(
            g.group_name, f"one-sided read from rank {rank} "
            f"(pid {desc['pid']}) failed — peer dead?: {e}") from e


def _onesided_allreduce(g: GroupMember, arr: np.ndarray,
                        flat: np.ndarray, op: ReduceOp):
    """All-same-host allreduce as pure one-sided reads: the rendezvous
    round exchanges (pid, address) descriptors for everyone's input,
    the fold reads peer chunks STRAIGHT out of their processes (no
    staging writes, no per-chunk messages), a second descriptor round
    publishes the reduced chunks, and the gather reads those.  The two
    extra coordinator rounds are the ONLY coordination — barriers that
    double as buffer-release acks."""
    w, r = g.world_size, g.rank
    sig = f"{op.value}:{arr.dtype.str}:{arr.nbytes}"
    t_rdv = time.time()
    rep = g.collect(f"rdv:allreduce:{sig}",
                    {"pid": _os_getpid(), "addr": int(flat.ctypes.data)})
    _tracing.record("collective", "collective.rendezvous", t_rdv,
                    time.time() - t_rdv,
                    trace=_tracing.current_dict())
    t_fold = time.time()
    descs = rep["gathered"]
    sl = _chunk_slices(flat.size, w)
    esz = flat.dtype.itemsize
    my = flat[sl[r]]
    acc = g.buf("acc", my.size, flat.dtype)
    stag = g.buf("stag", my.size, flat.dtype)
    if my.size:
        first = True
        for p in range(w):  # rank order == coordinator fold order
            if p == r:
                contrib = my
            else:
                _pvm_read(g, descs[p], stag, sl[r].start * esz,
                          my.nbytes, p)
                contrib = stag
            if first:
                np.copyto(acc, contrib)
                first = False
            else:
                _reduce_into(acc, contrib, op)
    _tracing.record("collective", "collective.fold", t_fold,
                    time.time() - t_fold,
                    trace=_tracing.current_dict())
    # Fold-done barrier doubling as the reduced-chunk publication; it
    # also guarantees every peer finished reading OUR input, so the
    # gather below may overwrite `flat` in place.
    t_gather = time.time()
    rep2 = g.collect(f"rdv:allreduce-ag:{sig}",
                     {"pid": _os_getpid(), "addr": int(acc.ctypes.data)})
    accs = rep2["gathered"]
    # In place when writable; otherwise a FRESH buffer — results may
    # outlive this op (async handles defer the write-back), so they
    # must never alias a recycled work buffer.
    out = flat if flat.flags.writeable else np.empty_like(flat)
    for p in range(w):
        if p == r:
            continue
        n = (sl[p].stop - sl[p].start) * esz
        if n:
            _pvm_read(g, accs[p], out[sl[p]], 0, n, p)
    out[sl[r]] = acc
    # No release round needed: peers read `acc` only during THEIR
    # gather, and we next mutate it after a future op's rendezvous —
    # which cannot complete until every peer left this gather.  (The
    # op-mismatch guard keeps this airtight: every synchronized op
    # opens with a collect round.)
    _tracing.record("collective", "collective.gather", t_gather,
                    time.time() - t_gather,
                    trace=_tracing.current_dict())
    return out.reshape(arr.shape)


def _os_getpid() -> int:
    import os
    return os.getpid()


def _fast_allreduce(g: GroupMember, arr: np.ndarray, op: ReduceOp):
    """Direct reduce-scatter + allgather on the transfer plane.

    When every peer is same-host (all exchanges are scratch memcpys,
    acked synchronously on send), the op runs IN PLACE on the input
    buffer: the result lands where the caller's tensor already lives
    and no fresh output pages are faulted.  Wire peers hold references
    to in-flight chunk views until acked, so a mixed/wire group uses a
    warm cached output buffer instead."""
    tr, eps = g.fast_plane()
    flat = np.ascontiguousarray(arr).reshape(-1)
    if _all_onesided(eps):
        return _onesided_allreduce(g, arr, flat, op)
    t_rdv = time.time()
    rep = g.collect(
        f"rdv:allreduce:{op.value}:{arr.dtype.str}:{arr.nbytes}", None)
    _tracing.record("collective", "collective.rendezvous", t_rdv,
                    time.time() - t_rdv,
                    trace=_tracing.current_dict())
    seq = rep["seq"]
    deadline = time.monotonic() + g._timeout()
    grp, w, r = g.group_name, g.world_size, g.rank
    all_shm = all(ep.same_host for ep in eps.values())
    if all_shm and flat.flags.writeable:
        out = flat  # in place: sends copy chunks to scratch eagerly
    else:
        # Fresh, not a cached work buffer: the result may be consumed
        # after later ops ran (async handles defer the write-back).
        out = np.empty_like(flat)
    sl = _chunk_slices(flat.size, w)
    esz = flat.dtype.itemsize
    sends: list = []
    handles: dict = {}
    try:
        # ---- reduce-scatter: everyone exchanges chunks pairwise ----
        t_rs = time.time()
        my = flat[sl[r]]
        for p, ep in eps.items():
            cp = flat[sl[p]]
            if cp.size:
                sends.append(tr.send(ep, (grp, seq, 0, r, p), cp,
                                     deadline, slot=f"rs{p}"))
        acc = g.buf("acc", my.size, flat.dtype)
        if my.size:
            for p, ep in eps.items():
                # Warm per-peer staging: one-sided reads and wire bytes
                # land here (scratch-arena peers return a direct view
                # of their arena instead and ignore the sink).
                stag = g.buf(f"stag{p}", my.size, flat.dtype)
                handles[(0, p)] = tr.recv(ep, (grp, seq, 0, p, r),
                                          my.nbytes, deadline, sink=stag)
            first = True
            for p in range(w):  # rank order == coordinator fold order
                if p == r:
                    contrib = my
                else:
                    contrib = handles[(0, p)].wait_array(flat.dtype)
                if first:
                    np.copyto(acc, contrib)
                    first = False
                else:
                    _reduce_into(acc, contrib, op)
                if p != r:
                    handles.pop((0, p)).release()
        if out is flat:
            # In-place output: peers may still be consuming our
            # reduce-scatter chunks; their acks must land before the
            # gather phase overwrites `flat`.
            _wait_sends(g, sends, deadline)
            sends = []
        _tracing.record("collective", "collective.reduce_scatter",
                        t_rs, time.time() - t_rs,
                        trace=_tracing.current_dict())
        # ---- allgather: each rank multicasts its reduced chunk ----
        t_ag = time.time()
        for p, ep in eps.items():
            n = (sl[p].stop - sl[p].start) * esz
            if n:
                handles[(1, p)] = tr.recv(ep, (grp, seq, 1, p, r), n,
                                          deadline, sink=out[sl[p]])
        if acc.size:
            sends += tr.multicast(
                [(ep, (grp, seq, 1, r, p)) for p, ep in eps.items()],
                acc, deadline, slot="ag")
        out[sl[r]] = acc
        for p in list(eps):
            h = handles.pop((1, p), None)
            if h is None:
                continue
            a = h.wait_array(flat.dtype)
            if not h.delivered_in_place:
                np.copyto(out[sl[p]], a)
            h.release()
        _wait_sends(g, sends, deadline)
        _tracing.record("collective", "collective.allgather", t_ag,
                        time.time() - t_ag,
                        trace=_tracing.current_dict())
    finally:
        for h in handles.values():
            try:
                h.release()
            except Exception:
                pass
    return out.reshape(arr.shape)


def _store_ring_allreduce(g: GroupMember, arr: np.ndarray, op: ReduceOp):
    """The pre-rewrite object-store ring (every chunk through
    ray_tpu.put/get plus a coordinator mailbox hop) — kept as the bench
    baseline and as a fallback plane (RT_COLLECTIVE_DATA_PLANE=store).
    Round ids now come from the coordinator rendezvous, so this path
    can no longer desync the group."""
    rep = g.collect(
        f"rdv:ringstore:{op.value}:{arr.dtype.str}:{arr.nbytes}", None)
    rid = rep["seq"]
    w, r = g.world_size, g.rank
    flat = arr.reshape(-1)
    n = flat.size
    pad = (-n) % w
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    chunks = [c.copy() for c in np.split(flat, w)]
    nxt, prv = (r + 1) % w, (r - 1) % w
    sent_refs = []  # keep owned until the ring drains (receivers borrow)

    def _pair(a, b):
        if op == ReduceOp.SUM:
            return a + b
        if op == ReduceOp.PRODUCT:
            return a * b
        if op == ReduceOp.MIN:
            return np.minimum(a, b)
        return np.maximum(a, b)

    timeout = g._timeout()
    for s in range(w - 1):
        send_idx = (r - s) % w
        recv_idx = (r - s - 1) % w
        ref = ray_tpu.put(chunks[send_idx])
        sent_refs.append(ref)
        # Wrapped in a tuple: a top-level ObjectRef arg would be resolved
        # to its value at the coordinator (standard arg semantics); nested
        # refs pass through, so only the tiny ref crosses the coordinator.
        g.put_mail(f"rs:{rid}:{s}:{r}->{nxt}", (ref,))
        got = g.get_mail(f"rs:{rid}:{s}:{prv}->{r}")[0]
        chunks[recv_idx] = _pair(chunks[recv_idx],
                                 ray_tpu.get(got, timeout=timeout))
    for s in range(w - 1):
        send_idx = (r + 1 - s) % w
        recv_idx = (r - s) % w
        ref = ray_tpu.put(chunks[send_idx])
        sent_refs.append(ref)
        g.put_mail(f"ag:{rid}:{s}:{r}->{nxt}", (ref,))
        got = g.get_mail(f"ag:{rid}:{s}:{prv}->{r}")[0]
        chunks[recv_idx] = np.asarray(ray_tpu.get(got, timeout=timeout))
    # Everyone has fetched everything once all members reach this point;
    # only then may the owned chunk refs be released.
    g.collect("barrier", None)
    del sent_refs
    out = np.concatenate(chunks)
    if pad:
        out = out[:n]
    return out.reshape(arr.shape)


# ------------------------------------------------- bucket fusion / handles
class CollectiveWork:
    """Handle for an in-flight collective op (``allreduce_async``,
    ``CollectiveBucket.allreduce_async``).  ``wait()`` blocks until the
    op finished, applies the write-back/unpack, and returns the result;
    exceptions (CollectiveGroupError included) re-raise there."""

    def __init__(self, fut, group: GroupMember, finalize=None):
        self._fut = fut
        self._group = group
        self._finalize = finalize
        self._done_result = _UNSET

    def done(self) -> bool:
        return self._fut.done()

    def wait(self, timeout: float | None = None):
        if self._done_result is not _UNSET:
            return self._done_result
        out = self._fut.result(
            timeout if timeout is not None
            else self._group._timeout() + 60)
        if self._finalize is not None:
            out = self._finalize(out)
        self._done_result = out
        return out


class CollectiveBucket:
    """Coalesces small same-dtype tensors into ONE flat buffer so they
    ride a single rendezvous + chunk exchange (bucket fusion — the
    DDP-style gradient bucketing).  ``indices`` remembers each tensor's
    position in the caller's original list so fused results can be
    reassembled in order."""

    def __init__(self, tensors, indices=None):
        tensors = [_as_numpy(t) for t in tensors]
        if not tensors:
            raise ValueError("empty bucket")
        dt = tensors[0].dtype
        for t in tensors:
            if t.dtype != dt:
                raise ValueError(
                    f"bucket mixes dtypes {dt} and {t.dtype}; "
                    "fuse_buckets partitions by dtype")
        self.tensors = tensors
        self.indices = list(indices) if indices is not None \
            else list(range(len(tensors)))
        self._shapes = [t.shape for t in tensors]
        self._sizes = [int(t.size) for t in tensors]
        if len(tensors) == 1 and tensors[0].flags.c_contiguous:
            # Single-tensor bucket: skip the pack copy and publish the
            # caller's buffer itself (same-host peers one-sided-read
            # straight out of it).  This keeps the SUBMIT side of
            # hook-ordered gradient overlap O(1) — the memcpy was the
            # dominant main-thread cost per bucket.  The caller must
            # not mutate the tensor until the op completes (the same
            # contract the in-place sync allreduce already has).
            self.flat = tensors[0].reshape(-1)
        else:
            self.flat = np.empty(sum(self._sizes), dtype=dt)
            pos = 0
            for t, n in zip(tensors, self._sizes):
                np.copyto(self.flat[pos:pos + n], t.reshape(-1))
                pos += n

    @property
    def nbytes(self) -> int:
        return self.flat.nbytes

    def unpack(self, reduced: np.ndarray) -> list:
        """Scatter the fused result back into the original tensors
        (in place when writable); returns them in bucket order."""
        outs, pos = [], 0
        for t, shape, n in zip(self.tensors, self._shapes, self._sizes):
            piece = reduced[pos:pos + n].reshape(shape)
            outs.append(_writeback(t, piece))
            pos += n
        return outs

    def allreduce_async(self, group_name: str = "default",
                        op: ReduceOp = ReduceOp.SUM) -> CollectiveWork:
        g = get_group_handle(group_name)
        # Per-bucket child span: fused buckets show up individually,
        # so comm/compute overlap is visible bucket by bucket.
        fut = g.run_op(lambda: _allreduce_impl(g, self.flat, op),
                       "allreduce_bucket", self.flat.nbytes)
        return CollectiveWork(fut, g, finalize=self.unpack)

    def allreduce(self, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM) -> list:
        return self.allreduce_async(group_name, op).wait()


def fuse_buckets(tensors, bucket_bytes: int | None = None
                 ) -> list[CollectiveBucket]:
    """Partition ``tensors`` into dtype-homogeneous buckets of about
    ``bucket_bytes`` (cfg.collective_bucket_bytes) each, preserving
    order within a dtype.  Every member must fuse the SAME tensor list
    in the same order — buckets consume group rounds like any op."""
    bb = max(1, bucket_bytes or cfg.collective_bucket_bytes)
    by_dtype: dict = {}
    for i, t in enumerate(tensors):
        a = _as_numpy(t)
        by_dtype.setdefault(a.dtype.str, []).append((i, t))
    buckets = []
    for _dt, entries in sorted(by_dtype.items()):
        cur, cur_idx, cur_bytes = [], [], 0
        for i, t in entries:
            nb = _as_numpy(t).nbytes
            if cur and cur_bytes + nb > bb:
                buckets.append(CollectiveBucket(cur, cur_idx))
                cur, cur_idx, cur_bytes = [], [], 0
            cur.append(t)
            cur_idx.append(i)
            cur_bytes += nb
        if cur:
            buckets.append(CollectiveBucket(cur, cur_idx))
    return buckets


def allreduce_coalesced(tensors, group_name: str = "default",
                        op: ReduceOp = ReduceOp.SUM,
                        bucket_bytes: int | None = None) -> list:
    """Allreduce many tensors through fused buckets with async overlap:
    all buckets are submitted before any is waited on, so bucket k+1's
    communication overlaps bucket k's unpack.  Returns the reduced
    tensors in input order (in place when writable)."""
    tensors = list(tensors)  # may be an iterator; consumed twice below
    buckets = fuse_buckets(tensors, bucket_bytes)
    works = [(b, b.allreduce_async(group_name, op)) for b in buckets]
    out = [None] * len(tensors)
    for b, wk in works:
        for idx, t in zip(b.indices, wk.wait()):
            out[idx] = t
    return out


# ------------------------------------------------------------- other ops
def allgather(tensor_list: list, tensor, group_name: str = "default"):
    """Gather each rank's tensor into tensor_list (reference: :423).
    Large tensors move peer-to-peer (each rank multicasts its tensor),
    so no process ever funnels O(world x bytes).

    Contract (reference semantics): every rank contributes the SAME
    shape and dtype.  The rendezvous signature pins them, so a
    mismatched contribution fails the group with a structured op
    mismatch error instead of silently corrupting the gather."""
    g = get_group_handle(group_name)
    arr = _as_numpy(tensor)
    gathered = g.run_op(lambda: _allgather_impl(g, arr),
                        "allgather", arr.nbytes).result(
        g._timeout() + 60)
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(gathered)
    return gathered


def _allgather_impl(g: GroupMember, arr: np.ndarray):
    if _plane_for(g, arr.nbytes) != "fast":
        return g.collect("gather", arr)
    tr, eps = g.fast_plane()
    if _all_onesided(eps):
        w, r = g.world_size, g.rank
        flat = np.ascontiguousarray(arr).reshape(-1)
        rep = g.collect(
            f"rdv:allgather:{arr.dtype.str}:{arr.nbytes}:{arr.shape}",
            {"pid": _os_getpid(), "addr": int(flat.ctypes.data)})
        descs = rep["gathered"]
        outs = [None] * w
        outs[r] = np.array(arr, copy=True)
        for p in range(w):
            if p == r:
                continue
            dst = np.empty(flat.size, flat.dtype)
            if flat.nbytes:
                _pvm_read(g, descs[p], dst, 0, flat.nbytes, p)
            outs[p] = dst.reshape(arr.shape)
        g.collect("barrier", None)  # release: all inputs fully read
        return outs
    rep = g.collect(
        f"rdv:allgather:{arr.dtype.str}:{arr.nbytes}:{arr.shape}", None)
    seq = rep["seq"]
    deadline = time.monotonic() + g._timeout()
    grp, w, r = g.group_name, g.world_size, g.rank
    flat = np.ascontiguousarray(arr).reshape(-1)
    outs: list = [None] * w
    outs[r] = np.array(arr, copy=True)
    handles = {}
    sends: list = []
    try:
        for p, ep in eps.items():
            dst = np.empty(flat.size, flat.dtype)
            outs[p] = dst
            handles[p] = tr.recv(ep, (grp, seq, 0, p, r), flat.nbytes,
                                 deadline, sink=dst)
        if flat.size:
            sends = tr.multicast(
                [(ep, (grp, seq, 0, r, p)) for p, ep in eps.items()],
                flat, deadline, slot="ga")
        for p in list(eps):
            h = handles.pop(p)
            a = h.wait_array(flat.dtype)
            if not h.delivered_in_place:
                np.copyto(outs[p], a)
            h.release()
            outs[p] = outs[p].reshape(arr.shape)
        _wait_sends(g, sends, deadline)
    finally:
        for h in handles.values():
            try:
                h.release()
            except Exception:
                pass
    return outs


def reducescatter(tensor, tensor_list: list, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM):
    """Reduce the per-rank lists elementwise; each rank keeps its slice
    (reference: :472).  Large entries move peer-to-peer: rank r sends
    tensor_list[p] straight to rank p and folds the w-1 contributions
    it receives in rank order (bit-identical to the coordinator's
    stacked fold)."""
    g = get_group_handle(group_name)
    arrs = [_as_numpy(t) for t in tensor_list]
    out = g.run_op(lambda: _reducescatter_impl(g, arrs, op),
                   "reducescatter",
                   sum(a.nbytes for a in arrs)).result(
        g._timeout() + 60)
    return _writeback(tensor, out)


def _reducescatter_impl(g: GroupMember, arrs: list, op: ReduceOp):
    if len(arrs) != g.world_size:
        raise ValueError(
            f"reducescatter needs one tensor per rank "
            f"({len(arrs)} != world size {g.world_size})")
    per = arrs[0].nbytes if arrs else 0
    if _plane_for(g, per) != "fast":
        reduced = g.collect(f"reduce:{op.value}", np.stack(arrs))
        return reduced[g.rank]
    tr, eps = g.fast_plane()
    a0 = arrs[0]
    if _all_onesided(eps):
        w, r = g.world_size, g.rank
        flats = [np.ascontiguousarray(a).reshape(-1) for a in arrs]
        mine = flats[r]
        rep = g.collect(
            f"rdv:reducescatter:{op.value}:{a0.dtype.str}:{a0.nbytes}:"
            f"{a0.shape}",
            {"pid": _os_getpid(),
             "addrs": [int(f.ctypes.data) for f in flats]})
        descs = rep["gathered"]
        acc = g.buf("acc", mine.size, mine.dtype)
        stag = g.buf("stag", mine.size, mine.dtype)
        if mine.size:
            first = True
            for p in range(w):  # rank order == coordinator fold order
                if p == r:
                    contrib = mine
                else:
                    d = descs[p]
                    _pvm_read(g, {"pid": d["pid"], "addr": d["addrs"][r]},
                              stag, 0, mine.nbytes, p)
                    contrib = stag
                if first:
                    np.copyto(acc, contrib)
                    first = False
                else:
                    _reduce_into(acc, contrib, op)
        g.collect("barrier", None)  # release: all inputs fully read
        return np.array(acc, copy=True).reshape(arrs[0].shape)
    rep = g.collect(
        f"rdv:reducescatter:{op.value}:{a0.dtype.str}:{a0.nbytes}:"
        f"{a0.shape}", None)
    seq = rep["seq"]
    deadline = time.monotonic() + g._timeout()
    grp, w, r = g.group_name, g.world_size, g.rank
    flats = [np.ascontiguousarray(a).reshape(-1) for a in arrs]
    mine = flats[r]
    handles = {}
    sends = []
    try:
        for p, ep in eps.items():
            stag = g.buf(f"stag{p}", mine.size, mine.dtype)
            handles[p] = tr.recv(ep, (grp, seq, 0, p, r), mine.nbytes,
                                 deadline, sink=stag)
            if flats[p].size:
                sends.append(tr.send(ep, (grp, seq, 0, r, p), flats[p],
                                     deadline, slot=f"rc{p}"))
        acc = None
        for p in range(w):  # rank order == coordinator fold order
            contrib = mine if p == r \
                else handles[p].wait_array(mine.dtype)
            if acc is None:
                acc = np.array(contrib, copy=True)
            else:
                _reduce_into(acc, contrib, op)
            if p != r:
                handles.pop(p).release()
        _wait_sends(g, sends, deadline)
    finally:
        for h in handles.values():
            try:
                h.release()
            except Exception:
                pass
    # Copy out of the cached accumulator: the caller's view must
    # survive later ops recycling the work buffers.
    return np.array(acc, copy=True).reshape(arrs[0].shape)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """Broadcast from src_rank (reference: :373).  Large tensors ride a
    binomial TREE on the data plane (log2(world) hops of the full
    tensor, each peer-to-peer); small ones take the coordinator."""
    g = get_group_handle(group_name)
    arr = _as_numpy(tensor)
    out = g.run_op(lambda: _broadcast_impl(g, arr, src_rank),
                   "broadcast", arr.nbytes).result(
        g._timeout() + 60)
    return _writeback(tensor, out)


def _broadcast_impl(g: GroupMember, arr: np.ndarray, src: int):
    if _plane_for(g, arr.nbytes) != "fast":
        payload = arr if g.rank == src else None
        return g.collect(f"src:{src}", payload)
    tr, eps = g.fast_plane()
    if _all_onesided(eps):
        r = g.rank
        flat = np.ascontiguousarray(arr).reshape(-1)
        desc = {"pid": _os_getpid(), "addr": int(flat.ctypes.data)} \
            if r == src else None
        rep = g.collect(
            f"rdv:broadcast:{src}:{arr.dtype.str}:{arr.nbytes}", desc)
        if r != src and flat.nbytes:
            buf = flat if flat.flags.writeable \
                else np.empty_like(flat)
            _pvm_read(g, rep["gathered"][src], buf, 0, flat.nbytes, src)
            flat = buf
        g.collect("barrier", None)  # release: source fully read by all
        return flat.reshape(arr.shape)
    rep = g.collect(
        f"rdv:broadcast:{src}:{arr.dtype.str}:{arr.nbytes}", None)
    seq = rep["seq"]
    deadline = time.monotonic() + g._timeout()
    grp, w, r = g.group_name, g.world_size, g.rank
    v = (r - src) % w  # virtual rank in the tree, root = 0
    flat = np.ascontiguousarray(arr).reshape(-1)
    if r != src:
        # Receive straight into the caller's tensor when writable
        # (broadcast overwrites it anyway) — no fresh pages.
        buf = flat if flat.flags.writeable \
            else np.empty_like(flat)
        k = v.bit_length() - 1
        sender = ((v - (1 << k)) + src) % w
        h = tr.recv(eps[sender], (grp, seq, 0, sender, r), buf.nbytes,
                    deadline, sink=buf)
        a = h.wait_array(flat.dtype)
        if not h.delivered_in_place:
            np.copyto(buf, a)
        h.release()
    else:
        buf = flat
    targets = []
    k = v.bit_length()
    while True:
        step = 1 << k
        if step >= w:
            break
        dstv = v + step
        if dstv < w:
            dst = (dstv + src) % w
            targets.append((eps[dst], (grp, seq, 0, r, dst)))
        k += 1
    sends = tr.multicast(targets, buf, deadline, slot="bc") \
        if targets else []
    _wait_sends(g, sends, deadline)
    return buf.reshape(arr.shape)


def barrier(group_name: str = "default"):
    """Block until every member arrives (reference: :298)."""
    g = get_group_handle(group_name)
    g.run_op(lambda: g.collect("barrier", None)).result(g._timeout() + 60)


def send(tensor, dst_rank: int, group_name: str = "default"):
    """Point-to-point send (reference: :531).  Bounded by
    cfg.collective_timeout_s like every other collective wait."""
    g = get_group_handle(group_name)
    tag = f"{group_name}:{g.rank}->{dst_rank}"
    g.put_mail(tag, _as_numpy(tensor))


def recv(tensor, src_rank: int, group_name: str = "default"):
    """Point-to-point recv (reference: :594)."""
    g = get_group_handle(group_name)
    tag = f"{group_name}:{src_rank}->{g.rank}"
    out = g.get_mail(tag)
    return _writeback(tensor, out)


class CollectiveMixin:
    """Mixin for actor classes whose instances join collective groups via
    create_collective_group from the driver."""

    def _rt_init_collective(self, world_size, rank, backend, group_name):
        init_collective_group(world_size, rank, backend, group_name)
        return True
