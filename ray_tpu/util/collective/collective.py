"""Collective communication across actors/tasks.

TPU-native re-design of the reference collective layer (reference:
python/ray/util/collective/collective.py — init_collective_group :120,
allreduce :258, barrier :298, broadcast :373, allgather :423,
reducescatter :472, send/recv :531/:594; NCCL backend via cupy in
collective_group/nccl_collective_group.py:127, gloo via pygloo).

On TPU the *tensor* plane never goes through host collectives: gradient
allreduce etc. compile to XLA collectives over ICI inside jit/pjit (see
ray_tpu.parallel).  What remains for the framework plane — rendezvous,
barriers, CPU-side state sync (e.g. RL rollout weights), cross-host
control — is served here by a coordinator actor per group (the reference's
gloo/NCCL rendezvous also rides a named store actor).  Members address the
group by name; the coordinator performs reductions on host numpy.
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.util.collective.types import ReduceOp

_groups: dict[str, "GroupMember"] = {}

_COORD_PREFIX = "_rt_collective_coord::"


def _reduce(arrays, op: ReduceOp):
    out = np.array(arrays[0], copy=True)
    for a in arrays[1:]:
        if op == ReduceOp.SUM:
            out = out + a
        elif op == ReduceOp.PRODUCT:
            out = out * a
        elif op == ReduceOp.MIN:
            out = np.minimum(out, a)
        elif op == ReduceOp.MAX:
            out = np.maximum(out, a)
    return out


class _Coordinator:
    """Async actor implementing barrier-synchronized group ops.  One per
    collective group, named, owned by whichever member created it first.

    Reductions happen ONCE here and only the result travels to each member
    (O(world) transfer per op, not O(world^2))."""

    def __init__(self, world_size: int):
        import asyncio
        self.world_size = world_size
        self._rounds: dict = {}
        self._results: dict = {}
        self._cond = asyncio.Condition()
        self._mailbox: dict = {}

    async def collect(self, mode, round_id, rank, data):
        """mode: "reduce:<op>" | "gather" | "src:<rank>" | "barrier"."""
        key = (mode, round_id)
        async with self._cond:
            slot = self._rounds.setdefault(key, {})
            slot[rank] = data
            self._cond.notify_all()
            while len(self._rounds.get(key, slot)) < self.world_size and \
                    key not in self._results:
                await self._cond.wait()
            if key not in self._results:
                full = self._rounds[key]
                if mode.startswith("reduce:"):
                    op = ReduceOp(mode.split(":", 1)[1])
                    result = _reduce([full[r] for r in sorted(full)], op)
                elif mode == "gather":
                    result = [full[r] for r in sorted(full)]
                elif mode.startswith("src:"):
                    result = full[int(mode.split(":", 1)[1])]
                else:
                    result = True
                self._results[key] = result
            # Last reader cleans the round up.
            reads = self._rounds.setdefault(("_reads",) + key, set())
            reads.add(rank)
            result = self._results[key]
            if len(reads) == self.world_size:
                self._rounds.pop(key, None)
                self._rounds.pop(("_reads",) + key, None)
                self._results.pop(key, None)
            return result

    async def put_mail(self, tag, data):
        import asyncio
        box = self._mailbox.setdefault(tag, asyncio.Queue())
        await box.put(data)
        return True

    async def get_mail(self, tag):
        import asyncio
        box = self._mailbox.setdefault(tag, asyncio.Queue())
        item = await box.get()
        # Ring tags are single-use and globally unique: drop drained
        # queues or a long training run leaks millions of them.
        if box.empty():
            self._mailbox.pop(tag, None)
        return item


class GroupMember:
    def __init__(self, group_name: str, world_size: int, rank: int):
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._round = 0
        coord_name = _COORD_PREFIX + group_name
        try:
            self.coord = ray_tpu.get_actor(coord_name)
        except ValueError:
            try:
                coord_cls = ray_tpu.remote(_Coordinator)
                self.coord = coord_cls.options(
                    name=coord_name, num_cpus=0).remote(world_size)
            except ValueError:
                self.coord = ray_tpu.get_actor(coord_name)

    def _next_round(self):
        self._round += 1
        return self._round

    def collect(self, mode, value):
        import os
        rid = self._next_round()
        timeout = float(os.environ.get("RT_COLLECTIVE_TIMEOUT_S", "3600"))
        return ray_tpu.get(
            self.coord.collect.remote(mode, rid, self.rank, value),
            timeout=timeout)

    def put_mail(self, tag, data, timeout=300.0):
        ray_tpu.get(self.coord.put_mail.remote(tag, data), timeout=timeout)

    def get_mail(self, tag, timeout=300.0):
        return ray_tpu.get(self.coord.get_mail.remote(tag),
                           timeout=timeout)


def init_collective_group(world_size: int, rank: int,
                          backend: str = "tcp",
                          group_name: str = "default") -> None:
    """Join this process to a named collective group (reference:
    collective.py:120)."""
    if group_name in _groups:
        raise RuntimeError(f"already in collective group '{group_name}'")
    _groups[group_name] = GroupMember(group_name, world_size, rank)


def create_collective_group(actors, world_size: int, ranks: list[int],
                            backend: str = "tcp",
                            group_name: str = "default"):
    """Declare a group across actor handles from the driver (reference:
    collective.py declare_collective_group): calls init on each member."""
    if len(actors) != len(ranks):
        raise ValueError(
            f"{len(actors)} actors but {len(ranks)} ranks")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(
            f"ranks {ranks} must be a permutation of 0..{world_size - 1}")
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(actor._rt_init_collective.remote(
            world_size, rank, backend, group_name))
    ray_tpu.get(refs, timeout=300)


def destroy_collective_group(group_name: str = "default") -> None:
    """Tear down the group's coordinator actor so the name can be reused
    with a different world size.  Works from any member OR from the driver
    that called create_collective_group."""
    _groups.pop(group_name, None)
    try:
        coord = ray_tpu.get_actor(_COORD_PREFIX + group_name)
        ray_tpu.kill(coord)
    except Exception:
        pass


def get_group_handle(group_name: str = "default") -> GroupMember:
    g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(
            f"not a member of collective group '{group_name}'; call "
            f"init_collective_group first")
    return g


def _as_numpy(tensor):
    return np.asarray(tensor)


# Tensors at/above this size take the ring path (object-store
# peer-to-peer chunks) instead of moving whole through the coordinator.
import os as _os
RING_THRESHOLD_BYTES = int(_os.environ.get("RT_RING_THRESHOLD_BYTES",
                                           1 << 22))


def allreduce(tensor, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM):
    """In-place allreduce of a host tensor across the group (reference:
    collective.py:258).  Device tensors are fetched to host; for on-device
    gradient reduction use XLA collectives via ray_tpu.parallel instead.

    Large tensors use a ring reduce-scatter + allgather whose chunks move
    member-to-member through the shared-memory object store — the
    coordinator relays only ObjectRefs, so no process ever handles
    O(world * bytes) (reference architecture: the NCCL ring in
    collective_group/nccl_collective_group.py:127; ours rides the
    framework's own data plane)."""
    g = get_group_handle(group_name)
    arr = _as_numpy(tensor)
    if arr.nbytes >= RING_THRESHOLD_BYTES and g.world_size > 2:
        out = _ring_allreduce(g, arr, op)
    else:
        out = g.collect(f"reduce:{op.value}", arr)
    try:
        tensor[...] = out
        return tensor
    except TypeError:
        return out


def _reduce_pair(a, b, op: ReduceOp):
    if op == ReduceOp.SUM:
        return a + b
    if op == ReduceOp.PRODUCT:
        return a * b
    if op == ReduceOp.MIN:
        return np.minimum(a, b)
    return np.maximum(a, b)


def _ring_allreduce(g: "GroupMember", arr: np.ndarray, op: ReduceOp):
    """Ring allreduce: W-1 reduce-scatter steps + W-1 allgather steps.
    Per-member traffic 2*(W-1)/W of the tensor, fully parallel across the
    ring; after reduce-scatter rank r owns complete chunk (r+1) % W."""
    w, r = g.world_size, g.rank
    rid = g._next_round()
    flat = arr.reshape(-1)
    n = flat.size
    pad = (-n) % w
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    chunks = [c.copy() for c in np.split(flat, w)]
    nxt, prv = (r + 1) % w, (r - 1) % w
    sent_refs = []  # keep owned until the ring drains (receivers borrow)

    for s in range(w - 1):
        send_idx = (r - s) % w
        recv_idx = (r - s - 1) % w
        ref = ray_tpu.put(chunks[send_idx])
        sent_refs.append(ref)
        # Wrapped in a tuple: a top-level ObjectRef arg would be resolved
        # to its value at the coordinator (standard arg semantics); nested
        # refs pass through, so only the tiny ref crosses the coordinator.
        g.put_mail(f"rs:{rid}:{s}:{r}->{nxt}", (ref,))
        got = g.get_mail(f"rs:{rid}:{s}:{prv}->{r}")[0]
        chunks[recv_idx] = _reduce_pair(
            chunks[recv_idx], ray_tpu.get(got, timeout=300), op)
    for s in range(w - 1):
        send_idx = (r + 1 - s) % w
        recv_idx = (r - s) % w
        ref = ray_tpu.put(chunks[send_idx])
        sent_refs.append(ref)
        g.put_mail(f"ag:{rid}:{s}:{r}->{nxt}", (ref,))
        got = g.get_mail(f"ag:{rid}:{s}:{prv}->{r}")[0]
        chunks[recv_idx] = np.asarray(ray_tpu.get(got, timeout=300))
    # Everyone has fetched everything once all members reach this point;
    # only then may the owned chunk refs be released.
    g.collect("barrier", None)
    del sent_refs
    out = np.concatenate(chunks)
    if pad:
        out = out[:n]
    return out.reshape(arr.shape)


def allgather(tensor_list: list, tensor, group_name: str = "default"):
    """Gather each rank's tensor into tensor_list (reference: :423)."""
    g = get_group_handle(group_name)
    gathered = g.collect("gather", _as_numpy(tensor))
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(gathered)
    return gathered


def reducescatter(tensor, tensor_list: list, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM):
    """Reduce the per-rank lists elementwise; each rank keeps its slice
    (reference: :472)."""
    g = get_group_handle(group_name)
    reduced = g.collect(f"reduce:{op.value}",
                        np.stack([_as_numpy(t) for t in tensor_list]))
    out = reduced[g.rank]
    try:
        tensor[...] = out
        return tensor
    except TypeError:
        return out


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """Broadcast from src_rank (reference: :373)."""
    g = get_group_handle(group_name)
    payload = _as_numpy(tensor) if g.rank == src_rank else None
    out = g.collect(f"src:{src_rank}", payload)
    try:
        tensor[...] = out
        return tensor
    except TypeError:
        return out


def barrier(group_name: str = "default"):
    """Block until every member arrives (reference: :298)."""
    g = get_group_handle(group_name)
    g.collect("barrier", None)


def send(tensor, dst_rank: int, group_name: str = "default"):
    """Point-to-point send (reference: :531)."""
    g = get_group_handle(group_name)
    tag = f"{group_name}:{g.rank}->{dst_rank}"
    ray_tpu.get(g.coord.put_mail.remote(tag, _as_numpy(tensor)), timeout=300)


def recv(tensor, src_rank: int, group_name: str = "default"):
    """Point-to-point recv (reference: :594)."""
    g = get_group_handle(group_name)
    tag = f"{group_name}:{src_rank}->{g.rank}"
    out = ray_tpu.get(g.coord.get_mail.remote(tag), timeout=300)
    try:
        tensor[...] = out
        return tensor
    except TypeError:
        return out


class CollectiveMixin:
    """Mixin for actor classes whose instances join collective groups via
    create_collective_group from the driver."""

    def _rt_init_collective(self, world_size, rank, backend, group_name):
        init_collective_group(world_size, rank, backend, group_name)
        return True
