"""Optional span export to OpenTelemetry (or any tracer-shaped object).

Reference: python/ray/util/tracing/tracing_helper.py — otel is imported
lazily (:35-59) and spans wrap task/actor submission+execution, with
context propagated inside the TaskSpec.  Here the propagation already
exists (trace ids ride every spec and land in `ray_tpu.timeline()`
chrome-trace args); this module bridges those same events to a live
tracer.  Enable per process:

    from ray_tpu.util import tracing
    tracing.enable_tracing()            # otel global tracer, if installed
    tracing.enable_tracing(my_tracer)   # or any object with start_span()

Worker processes inherit nothing automatically — enable inside the task/
actor (e.g. from the runtime env) exactly as the reference requires its
`--tracing-startup-hook`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

_tracer = None


def enable_tracing(tracer: Optional[Any] = None) -> None:
    """Register a tracer for this process.

    tracer contract (a subset of otel's Tracer): ``span =
    tracer.start_span(name, attributes=..., start_time=ns)`` then
    ``span.end(end_time=ns)``.  With tracer=None, uses
    ``opentelemetry.trace.get_tracer("ray_tpu")`` (raises ImportError if
    the optional dependency is absent, mirroring the reference's lazy
    import)."""
    global _tracer
    if tracer is None:
        from opentelemetry import trace as ot  # optional dependency
        tracer = ot.get_tracer("ray_tpu")
    _tracer = tracer


def disable_tracing() -> None:
    global _tracer
    _tracer = None


def is_enabled() -> bool:
    return _tracer is not None


def maybe_export(event: Dict) -> None:
    """Export one chrome-trace complete event ({ts,dur} in us; args
    carry trace_id/span_id/parent_id) as a span.  No-op unless
    enable_tracing() ran in this process; never raises into the
    runtime."""
    t = _tracer
    if t is None:
        return
    try:
        start_ns = int(event["ts"] * 1e3)
        end_ns = int((event["ts"] + event["dur"]) * 1e3)
        attrs = {"ray_tpu.category": event.get("cat", "")}
        for k in ("trace_id", "span_id", "parent_id"):
            v = (event.get("args") or {}).get(k)
            if v:
                attrs[f"ray_tpu.{k}"] = v
        span = t.start_span(event["name"], attributes=attrs,
                            start_time=start_ns)
        span.end(end_time=end_ns)
    except Exception:
        pass
