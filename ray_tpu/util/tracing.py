"""Optional span export to OpenTelemetry (or any tracer-shaped object).

Reference: python/ray/util/tracing/tracing_helper.py — otel is imported
lazily (:35-59) and spans wrap task/actor submission+execution, with
context propagated inside the TaskSpec.  Here the propagation already
exists (trace ids ride every spec and land in `ray_tpu.timeline()`
chrome-trace args); this module bridges those same events to a live
tracer.  Enable per process:

    from ray_tpu.util import tracing
    tracing.enable_tracing()            # otel global tracer, if installed
    tracing.enable_tracing(my_tracer)   # or any object with start_span()

Worker processes inherit nothing automatically — enable inside the task/
actor (e.g. from the runtime env) exactly as the reference requires its
`--tracing-startup-hook`.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

_tracer = None


def enable_tracing(tracer: Optional[Any] = None) -> None:
    """Register a tracer for this process.

    tracer contract (a subset of otel's Tracer): ``span =
    tracer.start_span(name, attributes=..., start_time=ns)`` then
    ``span.end(end_time=ns)``.  With tracer=None, uses
    ``opentelemetry.trace.get_tracer("ray_tpu")`` (raises ImportError if
    the optional dependency is absent, mirroring the reference's lazy
    import)."""
    global _tracer
    if tracer is None:
        from opentelemetry import trace as ot  # optional dependency
        tracer = ot.get_tracer("ray_tpu")
    _tracer = tracer
    _poke_span_runtime(tracer)


def disable_tracing() -> None:
    global _tracer
    _tracer = None
    _poke_span_runtime(None)


def _poke_span_runtime(tracer) -> None:
    """Tell the span ring (_private/tracing.py) whether a live exporter
    exists: its record() hot path then pays one identity check instead
    of a per-event module probe."""
    try:
        from ray_tpu._private import tracing as _rt
        _rt._LIVE_EXPORT = tracer
    except Exception:
        pass


def is_enabled() -> bool:
    return _tracer is not None


def _otel_links(args: Dict):
    """Parent/trace linkage as REAL otel links (SpanContext built from
    the propagated hex ids) instead of only string attributes — a
    backend that understands links renders the cross-process tree.
    Returns None when otel is absent or the event carries no parent."""
    parent = args.get("parent_id")
    tid = args.get("trace_id")
    if not parent or not tid:
        return None
    try:
        from opentelemetry import trace as ot
        ctx = ot.SpanContext(
            trace_id=int(tid, 16), span_id=int(parent, 16),
            is_remote=True,
            trace_flags=ot.TraceFlags(ot.TraceFlags.SAMPLED))
        return [ot.Link(ctx)]
    except Exception:
        return None


def maybe_export(event: Dict) -> None:
    """Export one chrome-trace complete event ({ts,dur} in us; args
    carry trace_id/span_id/parent_id) as a span — every plane's spans
    flow through here (_private/tracing.py record() calls this bridge
    for each ring append).  No-op unless enable_tracing() ran in this
    process; never raises into the runtime.

    Span linkage: when the real opentelemetry package is importable the
    parent/trace ids become an otel Link on the exported span; the
    string attributes remain for tracer-shaped test doubles and
    backends that ignore links."""
    t = _tracer
    if t is None:
        return
    try:
        start_ns = int(event["ts"] * 1e3)
        end_ns = int((event["ts"] + event["dur"]) * 1e3)
        args = event.get("args") or {}
        attrs = {"ray_tpu.category": event.get("cat", "")}
        for k in ("trace_id", "span_id", "parent_id"):
            v = args.get(k)
            if v:
                attrs[f"ray_tpu.{k}"] = v
        links = _otel_links(args)
        span = None
        if links is not None:
            try:
                span = t.start_span(event["name"], attributes=attrs,
                                    links=links, start_time=start_ns)
            except TypeError:
                span = None  # tracer contract without links kwarg
        if span is None:
            span = t.start_span(event["name"], attributes=attrs,
                                start_time=start_ns)
        span.end(end_time=end_ns)
    except Exception:
        pass
