"""Pluggable byte storage shared by tune (experiment sync) and workflow
(durable task/actor state).

Reference: tune/syncer.py + air/_internal/remote_storage.py — experiment
state and checkpoints sync through a storage abstraction addressed by
URI, so a head-node loss doesn't lose the experiment and resume works
from any machine.  Local filesystem ships in-tree; other schemes register
via `register_storage` (the reference delegates to pyarrow.fs — here the
seam is explicit and dependency-free).
"""

from __future__ import annotations

import os
import shutil
from typing import Callable, Dict


class Storage:
    """Byte-level KV over a URI prefix."""

    def write_bytes(self, rel: str, data: bytes) -> None:
        raise NotImplementedError

    def read_bytes(self, rel: str) -> bytes:
        raise NotImplementedError

    def exists(self, rel: str) -> bool:
        raise NotImplementedError

    def list_prefix(self, prefix: str) -> list:
        """Relative keys under `prefix` (workflow listings)."""
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> None:
        raise NotImplementedError

    def delete(self, rel: str) -> None:
        """Delete a single key; absent keys are a no-op."""
        raise NotImplementedError

    def write_bytes_if_absent(self, rel: str, data: bytes) -> bool:
        """Create `rel` only if it doesn't exist; True iff this call
        created it.  Backends with native atomic create (local files,
        GCS if-generation-match, S3 If-None-Match) should override —
        this default is check-then-write, atomic only per-process."""
        if self.exists(rel):
            return False
        self.write_bytes(rel, data)
        return True

    def upload_file(self, local_path: str, rel: str) -> None:
        with open(local_path, "rb") as f:
            self.write_bytes(rel, f.read())

    def download_file(self, rel: str, local_path: str) -> None:
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        with open(local_path, "wb") as f:
            f.write(self.read_bytes(rel))


class LocalStorage(Storage):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, rel: str) -> str:
        return os.path.join(self.root, rel)

    def write_bytes(self, rel: str, data: bytes) -> None:
        path = self._path(rel)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def read_bytes(self, rel: str) -> bytes:
        with open(self._path(rel), "rb") as f:
            return f.read()

    def exists(self, rel: str) -> bool:
        return os.path.exists(self._path(rel))

    def write_bytes_if_absent(self, rel: str, data: bytes) -> bool:
        # Write the full content to a tmp file first, then link() it into
        # place: link fails atomically if the key exists, and a crash can
        # never leave a partially-written (empty) key claiming the slot.
        path = self._path(rel)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.claim.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        try:
            os.link(tmp, path)
            return True
        except FileExistsError:
            return False
        finally:
            os.unlink(tmp)

    def delete(self, rel: str) -> None:
        try:
            os.unlink(self._path(rel))
        except FileNotFoundError:
            pass

    def upload_file(self, local_path: str, rel: str) -> None:
        dest = self._path(rel)
        if os.path.abspath(local_path) == os.path.abspath(dest):
            return  # experiment dir IS the storage root
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        shutil.copy2(local_path, dest)

    def list_prefix(self, prefix: str) -> list:
        base = self._path(prefix)
        out = []
        if not os.path.isdir(base):
            return out
        for root, _dirs, files in os.walk(base):
            for name in files:
                full = os.path.join(root, name)
                out.append(os.path.join(
                    prefix, os.path.relpath(full, base)))
        return sorted(out)

    def delete_prefix(self, prefix: str) -> None:
        shutil.rmtree(self._path(prefix), ignore_errors=True)


class MemStorage(Storage):
    """In-memory backend (scheme mem://) — the pluggability seam's test
    double, and a stand-in for object-store-backed storage."""

    _buckets: Dict[str, Dict[str, bytes]] = {}

    def __init__(self, bucket: str):
        self.data = MemStorage._buckets.setdefault(bucket, {})

    def write_bytes(self, rel: str, data: bytes) -> None:
        self.data[rel] = bytes(data)

    def read_bytes(self, rel: str) -> bytes:
        return self.data[rel]

    def exists(self, rel: str) -> bool:
        return rel in self.data

    def write_bytes_if_absent(self, rel: str, data: bytes) -> bool:
        new = bytes(data)
        return self.data.setdefault(rel, new) is new  # GIL-atomic

    def list_prefix(self, prefix: str) -> list:
        if not prefix.strip("/"):
            return sorted(self.data)
        p = prefix.rstrip("/") + "/"
        return sorted(k for k in self.data if k.startswith(p))

    def delete_prefix(self, prefix: str) -> None:
        p = prefix.rstrip("/") + "/"
        for k in list(self.data):
            if k.startswith(p):
                del self.data[k]

    def delete(self, rel: str) -> None:
        self.data.pop(rel, None)


_SCHEMES: Dict[str, Callable[[str], Storage]] = {
    "file": lambda rest: LocalStorage(rest),
    "mem": lambda rest: MemStorage(rest),
}


def register_storage(scheme: str, factory: Callable[[str], Storage]):
    """Plug a new URI scheme (e.g. "gs", "s3") into tune's sync path."""
    _SCHEMES[scheme] = factory


def get_storage(uri: str) -> Storage:
    """file:///path, mem://bucket, /plain/path -> Storage."""
    if "://" in uri:
        scheme, rest = uri.split("://", 1)
        if scheme not in _SCHEMES:
            raise ValueError(
                f"no storage backend for scheme {scheme!r} "
                f"(register one with tune.storage.register_storage)")
        return _SCHEMES[scheme](rest)
    return LocalStorage(uri)


def is_remote_uri(path: str) -> bool:
    return "://" in path and not path.startswith("file://")
