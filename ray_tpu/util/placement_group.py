"""Placement groups: gang scheduling of resource bundles.

Reference: python/ray/util/placement_group.py:127 placement_group() with
strategies PACK/SPREAD/STRICT_PACK/STRICT_SPREAD (:129-145); backed by the
GCS placement-group manager's 2-phase reservation.  TPU-era addition: TPU
bundles are placed on contiguous ICI sub-meshes (see _private/placement.py).
"""

from __future__ import annotations

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.object_ref import ObjectRef


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles=None):
        self.id = pg_id
        self._bundles = bundles or []

    @property
    def bundle_specs(self):
        return list(self._bundles)

    @property
    def bundle_count(self):
        return len(self._bundles)

    def ready(self) -> ObjectRef:
        """Returns an ObjectRef resolved when the PG is created (reference:
        PlacementGroup.ready())."""
        from ray_tpu import remote_function
        pg = self

        def _pg_ready():
            import ray_tpu
            ok = ray_tpu.wait_placement_group_ready(pg, timeout=120)
            if not ok:
                raise TimeoutError("placement group not ready")
            return True

        fn = remote_function.RemoteFunction(_pg_ready, num_cpus=0)
        return fn.remote()

    def wait(self, timeout_seconds: float = 60.0) -> bool:
        import ray_tpu
        return ray_tpu.wait_placement_group_ready(self, timeout_seconds)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))


def placement_group(bundles, strategy: str = "PACK", name: str = "",
                    lifetime=None) -> PlacementGroup:
    w = worker_mod.global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu.init() must be called first")
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"invalid placement strategy {strategy}")
    pg_id = PlacementGroupID.from_random()
    w._run(w._gcs_request("create_placement_group", {
        "pg_id": pg_id, "bundles": list(bundles), "strategy": strategy,
        "name": name, "job_id": w.job_id}))
    return PlacementGroup(pg_id, list(bundles))


def remove_placement_group(pg: PlacementGroup):
    w = worker_mod.global_worker
    w._run(w._gcs_request("remove_placement_group", {"pg_id": pg.id}))


def get_placement_group_state(pg: PlacementGroup):
    w = worker_mod.global_worker
    view = w._run(w._gcs_request("get_placement_group", {"pg_id": pg.id}))
    return view


def placement_group_table():
    w = worker_mod.global_worker
    return w._run(w._gcs_request("list_placement_groups", {}))
