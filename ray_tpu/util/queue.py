"""Distributed Queue: a named FIFO shared across tasks/actors.

Reference: python/ray/util/queue.py — Queue backed by an actor; put/get
with block/timeout semantics from any process in the cluster.
"""

from __future__ import annotations

from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        import asyncio
        self.maxsize = maxsize
        self.q = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: Optional[float] = None):
        import asyncio
        try:
            if timeout is None:
                await self.q.put(item)
            else:
                await asyncio.wait_for(self.q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        import asyncio
        try:
            if timeout is None:
                return True, await self.q.get()
            return True, await asyncio.wait_for(self.q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    def put_nowait_batch(self, items: List) -> bool:
        if self.maxsize > 0 and self.q.qsize() + len(items) > self.maxsize:
            return False
        for item in items:
            self.q.put_nowait(item)
        return True

    def qsize(self) -> int:
        return self.q.qsize()

    def empty(self) -> bool:
        return self.q.empty()

    def full(self) -> bool:
        return self.q.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        opts.setdefault("max_concurrency", 1000)
        cls = ray_tpu.remote(_QueueActor)
        self.actor = cls.options(**opts).remote(maxsize)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None):
        ok = ray_tpu.get(self.actor.put.remote(
            item, timeout if block else 0.001), timeout=None)
        if not ok:
            raise Full("queue full")

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        ok, item = ray_tpu.get(self.actor.get.remote(
            timeout if block else 0.001), timeout=None)
        if not ok:
            raise Empty("queue empty")
        return item

    def put_nowait(self, item):
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: List):
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items)),
                           timeout=60):
            raise Full("queue full")

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote(), timeout=60)

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote(), timeout=60)

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote(), timeout=60)

    def shutdown(self):
        try:
            ray_tpu.kill(self.actor)
        except Exception:
            pass
