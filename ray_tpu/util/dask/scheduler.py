"""A dask scheduler over this runtime's task graph.

Reference behavior: python/ray/util/dask/scheduler.py:83
(``ray_dask_get``), :510 (``ray_dask_get_sync``), :32
(``enable_dask_on_ray``).  See the package docstring for why this
implementation submits the graph in one pass instead of reusing
dask's thread-pooled ``get_async``.

Graph protocol (dask's documented spec, implemented natively):

* a *task* is a tuple whose first element is callable: ``(add, 'x', 1)``
* lists are traversed structurally (may contain tasks / key refs)
* any other hashable value that is a key of the graph is a reference
  to that key's computed value; everything else is a literal
* non-task tuples are NOT traversed — they are either keys
  (dask uses tuple keys like ``('x', 0)``) or literals
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List, Set

import ray_tpu

# Literal graph values at or above this size are put() once and shared
# by reference instead of being re-pickled into every dependent task.
_PUT_THRESHOLD = 64 * 1024


def _istask(x: Any) -> bool:
    return isinstance(x, tuple) and len(x) > 0 and callable(x[0])


def _find_deps(value: Any, keyset: Set[Hashable], out: Set[Hashable]):
    """Collect graph keys referenced by ``value``.

    Must mirror ``_execute_value`` exactly: what the worker would
    substitute is what the driver must wire as a dependency.
    """
    if _istask(value):
        for a in value[1:]:
            _find_deps(a, keyset, out)
    elif isinstance(value, list):
        for a in value:
            _find_deps(a, keyset, out)
    else:
        try:
            if value in keyset:
                out.add(value)
        except TypeError:
            pass  # unhashable literal


def _has_tasks(value: Any) -> bool:
    """Does ``value`` contain any task tuple needing execution?"""
    if _istask(value):
        return True
    if isinstance(value, list):
        return any(_has_tasks(a) for a in value)
    return False


def _execute_value(value: Any, env: Dict[Hashable, Any]) -> Any:
    """Evaluate one graph value on the worker: run nested task tuples
    depth-first, rebuild lists, substitute key references from env."""
    if _istask(value):
        fn = value[0]
        args = [_execute_value(a, env) for a in value[1:]]
        return fn(*args)
    if isinstance(value, list):
        return [_execute_value(a, env) for a in value]
    try:
        if value in env:
            return env[value]
    except TypeError:
        pass
    return value


def _dask_task(payload: Any, dep_keys: List[Hashable], *dep_values):
    """One graph node as a remote task.  ``dep_values`` arrive as plain
    values — the runtime resolved any ObjectRef arguments before
    dispatch, which is exactly the readiness gate dask's local
    scheduler implements with a thread pool."""
    env = dict(zip(dep_keys, dep_values))
    return _execute_value(payload, env)


def _reject_new_task_spec(dsk: Dict[Hashable, Any]) -> None:
    """dask >= 2024.12 replaced tuple-tasks with ``dask._task_spec``
    node objects (Task/Alias/DataNode).  Those would pass through
    ``_istask`` as literals and silently return unexecuted nodes, so
    fail loudly instead.  New-spec graphs can be lowered to the tuple
    protocol with ``dask._task_spec.convert_legacy_graph``'s inverse
    or by pinning dask < 2024.12; this module targets the documented
    tuple protocol, which needs no dask at all."""
    for v in dsk.values():
        mod = type(v).__module__
        if mod and mod.startswith("dask._task_spec"):
            raise NotImplementedError(
                "this graph uses dask's new task-spec nodes "
                f"({type(v).__name__}); ray_dask_get executes the "
                "legacy tuple protocol — materialize the graph with "
                "dask<2024.12 or convert it to tuple tasks first")


def _toposort(deps: Dict[Hashable, Set[Hashable]]) -> List[Hashable]:
    """Kahn's algorithm; raises on cycles."""
    pending = {k: set(v) for k, v in deps.items()}
    dependents: Dict[Hashable, List[Hashable]] = {k: [] for k in deps}
    for k, ds in deps.items():
        for d in ds:
            dependents[d].append(k)
    ready = [k for k, ds in pending.items() if not ds]
    order: List[Hashable] = []
    while ready:
        k = ready.pop()
        order.append(k)
        for dep in dependents[k]:
            pending[dep].discard(k)
            if not pending[dep]:
                ready.append(dep)
    if len(order) != len(deps):
        cyclic = sorted(
            (str(k) for k, ds in pending.items() if ds))[:5]
        raise ValueError(f"cycle in dask graph involving keys {cyclic}")
    return order


def _sizeof(x: Any) -> int:
    try:
        if hasattr(x, "nbytes"):
            return int(x.nbytes)
        import sys
        return sys.getsizeof(x)
    except Exception:
        return 0


def ray_dask_get(dsk: Dict[Hashable, Any], keys, **kwargs):
    """Compute ``keys`` (a key or arbitrarily nested lists of keys)
    from dask graph ``dsk`` on the cluster.

    Pass directly to ``dask.compute(obj, scheduler=ray_dask_get)`` or
    use on hand-built graph dicts — the graph protocol does not
    require dask itself.

    Supported kwargs (mirroring the reference's surface):
      * ``ray_remote_args``: options applied to every graph task
        (e.g. ``{"num_cpus": 1, "resources": {...}}``).
      * ``ray_persist``: return ObjectRefs instead of values
        (the reference's ``ray_persist=True`` used by ``dask.persist``).
    Other scheduler kwargs dask passes (``num_workers``, ``pool``) are
    accepted and ignored: submission here is a single non-blocking
    pass, so there is no submission pool to size.
    """
    ray_remote_args = dict(kwargs.pop("ray_remote_args", None) or {})
    persist = bool(kwargs.pop("ray_persist", False))

    _reject_new_task_spec(dsk)
    keyset = set(dsk)
    deps: Dict[Hashable, Set[Hashable]] = {}
    for k, v in dsk.items():
        d: Set[Hashable] = set()
        _find_deps(v, keyset, d)
        deps[k] = d  # a self-reference stays: toposort reports it as a cycle

    task = ray_tpu.remote(_dask_task)
    if ray_remote_args:
        task = task.options(**ray_remote_args)

    refs: Dict[Hashable, Any] = {}    # key -> ObjectRef
    cache: Dict[Hashable, Any] = {}   # key -> local literal
    for k in _toposort(deps):
        v = dsk[k]
        kdeps = deps[k]
        if not kdeps and not _has_tasks(v):
            # Literal (including task-free lists): keep local; share
            # big ones by reference.
            if _sizeof(v) >= _PUT_THRESHOLD:
                refs[k] = ray_tpu.put(v)
            else:
                cache[k] = v
            continue
        is_alias = False
        try:
            is_alias = v in keyset
        except TypeError:
            pass
        if is_alias:
            if v in refs:
                refs[k] = refs[v]
            else:
                cache[k] = cache[v]
            continue
        dep_keys = sorted(kdeps, key=str)
        dep_vals = [refs[d] if d in refs else cache[d]
                    for d in dep_keys]
        refs[k] = task.remote(v, dep_keys, *dep_vals)

    def _missing(key):
        raise KeyError(f"requested key {key!r} not in dask graph")

    if persist:
        def _pack_ref(ks):
            if isinstance(ks, list):
                return [_pack_ref(x) for x in ks]
            if ks in refs:
                return refs[ks]
            if ks in cache:
                return ray_tpu.put(cache[ks])
            _missing(ks)
        return _pack_ref(keys)

    # Gather every needed ref once (deduped), then repack.
    needed: List[Any] = []
    seen: Dict[Any, int] = {}

    def _collect(ks):
        if isinstance(ks, list):
            for x in ks:
                _collect(x)
        elif ks in refs:
            r = refs[ks]
            if r not in seen:
                seen[r] = len(needed)
                needed.append(r)
        elif ks not in cache:
            _missing(ks)
    _collect(keys)
    values = ray_tpu.get(needed) if needed else []

    def _pack(ks):
        if isinstance(ks, list):
            return [_pack(x) for x in ks]
        if ks in refs:
            return values[seen[refs[ks]]]
        return cache[ks]
    return _pack(keys)


def ray_dask_get_sync(dsk, keys, **kwargs):
    """Reference parity alias (scheduler.py:510): the reference's sync
    variant exists to skip its submission thread pool; submission here
    is already a single synchronous pass, so both entry points share
    one implementation."""
    return ray_dask_get(dsk, keys, **kwargs)


_saved_dask_config: List[tuple] = []


def enable_dask_on_ray(shuffle: str = "tasks") -> None:
    """Install ``ray_dask_get`` as dask's global default scheduler
    (reference: scheduler.py:32).  Requires dask itself."""
    import dask
    _saved_dask_config.append((dask.config.get("scheduler", None),
                               dask.config.get("shuffle", None)))
    dask.config.set(scheduler=ray_dask_get, shuffle=shuffle)


def disable_dask_on_ray() -> None:
    """Restore the scheduler/shuffle config active before
    ``enable_dask_on_ray``; a no-op when there is nothing to undo
    (an unmatched disable must not wipe the user's own config)."""
    if not _saved_dask_config:
        return
    import dask
    prev_sched, prev_shuffle = _saved_dask_config.pop()
    dask.config.set(scheduler=prev_sched, shuffle=prev_shuffle)
