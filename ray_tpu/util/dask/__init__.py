"""Dask-on-Ray: execute dask task graphs on the cluster.

Reference: python/ray/util/dask/scheduler.py:83 ``ray_dask_get`` — a
dask scheduler that ships each top-level graph task to the cluster and
repacks results into dask collections; ``enable_dask_on_ray`` installs
it as dask's global scheduler (util/dask/__init__.py).

Re-designed for this runtime rather than translated: the reference
hijacks dask's thread-pooled ``get_async`` loop, blocking one thread
per in-flight task to discover readiness.  Here the whole graph is
submitted in ONE topological pass — every dask task becomes a remote
task whose dependency arguments are ObjectRefs, and the runtime's own
submitter-side DependencyResolver gates dispatch, so no thread pool,
no readiness polling, and downstream tasks are queued cluster-side the
moment their inputs seal.

The dask *graph protocol* is a plain-dict contract (key -> literal |
key-reference | task tuple ``(callable, *args)`` with nested lists),
so this module implements it natively and is fully testable without
dask installed; ``enable_dask_on_ray`` additionally wires dask's
config when the real library is present.
"""

from ray_tpu.util.dask.scheduler import (  # noqa: F401
    disable_dask_on_ray,
    enable_dask_on_ray,
    ray_dask_get,
    ray_dask_get_sync,
)

__all__ = [
    "ray_dask_get",
    "ray_dask_get_sync",
    "enable_dask_on_ray",
    "disable_dask_on_ray",
]
