"""XLA/TPU device profiling (the accelerator-side complement of
`ray_tpu.timeline()`'s host-side chrome trace).

The reference's `ray timeline` shows task/actor scheduling; what it
cannot show is where the chip time goes inside a jitted step.  This
wraps `jax.profiler` so a trace lands in the session directory (or any
dir) and can be opened in TensorBoard/Perfetto, and works inside remote
tasks/actors — each process writes to its own subdirectory, so a gang
profile is one directory tree.

    from ray_tpu.util import tpu_profiler

    with tpu_profiler.trace():          # session-dir default
        state, m = step(state, tokens)

    tpu_profiler.start(); ...; path = tpu_profiler.stop()
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Optional

_active_dir: Optional[str] = None


def default_trace_dir() -> str:
    """<session_dir>/tpu_profile/<pid> when a runtime session exists,
    else /tmp/ray_tpu/tpu_profile/<pid>."""
    base = os.environ.get("RT_SESSION_DIR", "/tmp/ray_tpu")
    try:
        from ray_tpu._private import api as _api
        node = getattr(_api, "_head_node", None)
        if node is not None and getattr(node, "session_dir", None):
            base = node.session_dir
    except Exception:
        pass
    return os.path.join(base, "tpu_profile",
                        f"{int(time.time())}-{os.getpid()}")


def start(trace_dir: Optional[str] = None) -> str:
    """Begin capturing a device trace; returns the trace directory."""
    global _active_dir
    if _active_dir is not None:
        raise RuntimeError(f"a trace is already active: {_active_dir}")
    import jax
    d = trace_dir or default_trace_dir()
    os.makedirs(d, exist_ok=True)
    jax.profiler.start_trace(d)
    _active_dir = d
    return d


def stop() -> str:
    """Finish the capture; returns the directory holding the trace
    (open with `tensorboard --logdir <dir>` or upload the contained
    .trace.json.gz to Perfetto)."""
    global _active_dir
    if _active_dir is None:
        raise RuntimeError("no active trace (call start() first)")
    import jax
    # Clear the guard FIRST: a failing stop_trace must not wedge every
    # later start() with "a trace is already active".
    d, _active_dir = _active_dir, None
    jax.profiler.stop_trace()
    return d


@contextlib.contextmanager
def trace(trace_dir: Optional[str] = None):
    """Context manager: profile the enclosed device work."""
    d = start(trace_dir)
    try:
        yield d
    finally:
        stop()


def annotate(name: str):
    """Label a region so it shows up named in the trace (wraps
    jax.profiler.TraceAnnotation)."""
    import jax
    return jax.profiler.TraceAnnotation(name)
