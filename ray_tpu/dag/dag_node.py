"""Lazy bind-don't-execute IR: fn.bind(x) builds a DAGNode graph.

Reference: python/ray/dag/dag_node.py:22 — DAGNode with FunctionNode /
ClassNode / ClassMethodNode / InputNode subclasses; `.execute()` walks the
graph submitting tasks/actors; Serve graphs compile from the same IR.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """One node: an op plus bound (possibly nested-DAGNode) args."""

    def __init__(self, args: Tuple, kwargs: Dict,
                 options: Optional[Dict] = None):
        self._bound_args = tuple(args)
        self._bound_kwargs = dict(kwargs)
        self._bound_options = dict(options or {})
        self._stable_uuid = uuid.uuid4().hex

    # ------------------------------------------------------------ traversal
    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _apply_recursive(self, fn, memo: Optional[Dict] = None):
        """Bottom-up transform returning fn(node, resolved_args,
        resolved_kwargs); shared nodes resolve once."""
        memo = {} if memo is None else memo
        if self._stable_uuid in memo:
            return memo[self._stable_uuid]

        def _res(v):
            return v._apply_recursive(fn, memo) if isinstance(v, DAGNode) \
                else v

        args = tuple(_res(a) for a in self._bound_args)
        kwargs = {k: _res(v) for k, v in self._bound_kwargs.items()}
        out = fn(self, args, kwargs)
        memo[self._stable_uuid] = out
        return out

    # ------------------------------------------------------------ execution
    def execute(self, *input_args, **input_kwargs):
        """Run the graph through the runtime; returns the root's result
        handle (ObjectRef / ActorHandle / value)."""
        ctx = {"args": input_args, "kwargs": input_kwargs}

        def _exec(node, args, kwargs):
            return node._execute_impl(args, kwargs, ctx)

        return self._apply_recursive(_exec)

    def _execute_impl(self, args, kwargs, ctx):
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for the value supplied at execute() time (reference:
    dag/input_node.py).  Usable as a context manager for symmetry with the
    reference API: `with InputNode() as inp: ...`."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _execute_impl(self, args, kwargs, ctx):
        a = ctx["args"]
        if len(a) == 1 and not ctx["kwargs"]:
            return a[0]
        return a if a else None


class FunctionNode(DAGNode):
    """fn.bind(...) (reference: dag/function_node.py)."""

    def __init__(self, fn, args, kwargs, options=None):
        super().__init__(args, kwargs, options)
        self._fn = fn

    def _execute_impl(self, args, kwargs, ctx):
        import ray_tpu
        rf = ray_tpu.remote(self._fn)
        if self._bound_options:
            rf = rf.options(**self._bound_options)
        # Upstream ObjectRefs pass through as task args (the runtime
        # resolves them worker-side, preserving parallelism).
        return rf.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """Cls.bind(...) — instantiates the actor at execute time (reference:
    dag/class_node.py)."""

    def __init__(self, cls, args, kwargs, options=None):
        super().__init__(args, kwargs, options)
        self._cls = cls

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _UnboundClassMethod(self, name)

    def _execute_impl(self, args, kwargs, ctx):
        import ray_tpu
        ac = ray_tpu.remote(self._cls)
        if self._bound_options:
            ac = ac.options(**self._bound_options)
        return ac.remote(*args, **kwargs)


class _UnboundClassMethod:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name,
                               args, kwargs)


class ClassMethodNode(DAGNode):
    """actor_node.method.bind(...) (reference: dag/class_node.py
    ClassMethodNode)."""

    def __init__(self, class_node: ClassNode, method_name: str,
                 args, kwargs):
        super().__init__((class_node,) + tuple(args), kwargs)
        self._method_name = method_name

    def _execute_impl(self, args, kwargs, ctx):
        actor_handle, *rest = args
        method = getattr(actor_handle, self._method_name)
        return method.remote(*rest, **kwargs)
