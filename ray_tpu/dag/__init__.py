"""ray_tpu.dag: lazy call-graph IR (reference: python/ray/dag)."""

from ray_tpu.dag.dag_node import (  # noqa: F401
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputNode,
)

__all__ = ["ClassMethodNode", "ClassNode", "DAGNode", "FunctionNode",
           "InputNode"]
