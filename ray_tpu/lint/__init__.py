"""ray_tpu.lint: an AST-based distributed-correctness linter.

Ray-style programs fail in ways no general-purpose linter sees:
serialized `get()` loops, leaked ObjectRefs, closures that drag a
module-level array (or a lock) into every task, blocking `get()` inside
a worker that deadlocks a fixed-size pool.  `util/check_serialize.py`
catches one of these classes at runtime; this package catches them
*statically*, before anything runs on a TPU slice.

Usage:

    python -m ray_tpu.lint ray_tpu examples tests
    rt lint ray_tpu examples tests          # CLI alias

Suppression: a `# noqa` or `# noqa: RTL004` comment on the flagged line.
Incremental adoption: a JSON baseline file (`--write-baseline`) records
current per-file/per-code counts; only findings beyond the baseline
fail the run.

Rule codes (see ray_tpu/lint/rules.py for the implementations):

    RTL001  get() inside a loop on refs produced in that loop
    RTL002  .remote() result discarded
    RTL003  large module-level np/jnp array captured by a remote closure
    RTL004  blocking get()/wait() inside a remote function/actor method
    RTL005  actor method called without .remote()
    RTL006  statically-unserializable capture (locks, files, generators)
    RTL007  jax/jnp compute in a task that requests no TPU resources
    RTL008  wait() misuse (wrong unpack, get(wait(...)), timeout=0 spin)

The RTC1xx family (ray_tpu/lint/concurrency.py) turns the same engine
on ray_tpu's OWN internals — lock discipline, lock-order deadlock
cycles, blocking calls under a held lock, and unlocked objects escaping
into spawned threads.  RTC102 is a *package-scope* rule: it merges a
per-module summary (PackageRule.summarize) into one whole-tree
acquired-while-held graph and reports cycles (PackageRule.check_package).

    RTC101  attribute written both under a class lock and bare
    RTC102  lock-order cycle (potential deadlock) across the package
    RTC103  blocking call (get/wait/sleep/subprocess/cond-wait) under a lock
    RTC104  object handed to a thread with no lock but mutated attributes
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

__all__ = [
    "Finding", "Rule", "PackageRule", "ModuleContext", "register_rule",
    "register_package_rule", "all_rules", "all_package_rules",
    "lint_source", "lint_file", "lint_paths", "collect_summaries",
    "load_baseline", "write_baseline", "apply_baseline",
    "baseline_key",
]

# The names ray_tpu exports that the rules care about.  Aliased imports
# (`import ray_tpu as ray`, `from ray_tpu import get as fetch`) are
# resolved per-module by ModuleContext.
_API_BLOCKING = {"get", "wait"}
_API_NAMES = _API_BLOCKING | {"put", "remote", "kill", "get_actor", "init"}
_MODULE_NAMES = {"ray_tpu", "ray"}


@dataclass(frozen=True)
class Finding:
    """One lint hit, pointing at a source location."""

    code: str
    message: str
    path: str
    line: int
    col: int
    severity: str = "error"  # "error" | "warning"

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.code} [{self.severity}] {self.message}")


class Rule:
    """Base class: subclasses set the class attrs and implement check().

    Registration is explicit via @register_rule so importing the rules
    module is what populates the registry (no metaclass magic)."""

    code: str = ""
    name: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, ctx: "ModuleContext") -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "ModuleContext", node: ast.AST,
                message: str) -> Finding:
        return Finding(code=self.code, message=message, path=ctx.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       severity=self.severity)


class PackageRule:
    """A whole-package rule: sees every linted module at once.

    Per-module facts are extracted by ``summarize(ctx)`` into a plain
    picklable dict (so ``--jobs`` workers can compute them in parallel
    without shipping ASTs); ``check_package`` then runs ONCE over the
    merged summary list.  Summaries must carry no AST nodes."""

    code: str = ""
    name: str = ""
    severity: str = "error"
    description: str = ""

    def summarize(self, ctx: "ModuleContext") -> dict:
        raise NotImplementedError

    def check_package(self, summaries: List[dict]) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Type[Rule]] = {}
_PACKAGE_REGISTRY: Dict[str, Type[PackageRule]] = {}


def register_rule(cls: Type[Rule]) -> Type[Rule]:
    if not cls.code:
        raise ValueError(f"rule {cls.__name__} has no code")
    if cls.code in _REGISTRY or cls.code in _PACKAGE_REGISTRY:
        raise ValueError(f"duplicate rule code {cls.code}")
    _REGISTRY[cls.code] = cls
    return cls


def register_package_rule(cls: Type[PackageRule]) -> Type[PackageRule]:
    if not cls.code:
        raise ValueError(f"rule {cls.__name__} has no code")
    if cls.code in _REGISTRY or cls.code in _PACKAGE_REGISTRY:
        raise ValueError(f"duplicate rule code {cls.code}")
    _PACKAGE_REGISTRY[cls.code] = cls
    return cls


def _load_rules():
    from ray_tpu.lint import concurrency, rules  # noqa: F401


def all_rules() -> Dict[str, Type[Rule]]:
    _load_rules()
    return dict(_REGISTRY)


def all_package_rules() -> Dict[str, Type[PackageRule]]:
    _load_rules()
    return dict(_PACKAGE_REGISTRY)


_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z][A-Z0-9 ,]*))?",
                      re.IGNORECASE)


class ModuleContext:
    """Everything the rules need about one parsed module: the tree with
    parent links, which local names alias the ray_tpu module/API, which
    defs are remote, and per-line noqa suppressions."""

    def __init__(self, tree: ast.Module, source: str, path: str):
        self.tree = tree
        self.path = path
        self.lines = source.splitlines()
        self.noqa: Dict[int, Optional[set]] = self._scan_noqa()
        # child -> parent links for scope walks.
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        # Local aliases of the ray_tpu module and its API functions.
        self.module_aliases: set = set()
        self.api_aliases: Dict[str, str] = {}  # local name -> api name
        self.jax_aliases: set = set()
        self.np_aliases: set = set()
        self._scan_imports()
        # Remote defs: FunctionDef/ClassDef carrying @remote (any
        # spelling), name -> (node, options dict of decorator kwargs).
        self.remote_functions: Dict[str, Tuple[ast.AST, dict]] = {}
        self.remote_classes: Dict[str, Tuple[ast.AST, dict]] = {}
        self._scan_remote_defs()

    # ------------------------------------------------------------ noqa
    def _scan_noqa(self) -> Dict[int, Optional[set]]:
        out: Dict[int, Optional[set]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _NOQA_RE.search(line)
            if not m:
                continue
            codes = m.group("codes")
            if codes:
                out[i] = {c.strip().upper()
                          for c in codes.split(",") if c.strip()}
            else:
                out[i] = None  # bare noqa: suppress everything
        return out

    def suppressed(self, f: Finding) -> bool:
        if f.line not in self.noqa:
            return False
        codes = self.noqa[f.line]
        return codes is None or f.code in codes

    # --------------------------------------------------------- imports
    def _scan_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    root = alias.name.split(".")[0]
                    bound = alias.asname or root
                    if root in _MODULE_NAMES:
                        self.module_aliases.add(bound)
                    elif root == "jax":
                        self.jax_aliases.add(bound)
                    elif root == "numpy":
                        self.np_aliases.add(bound)
            elif isinstance(node, ast.ImportFrom):
                if node.module and node.module.split(".")[0] \
                        in _MODULE_NAMES:
                    for alias in node.names:
                        if alias.name in _API_NAMES:
                            self.api_aliases[alias.asname or alias.name] \
                                = alias.name
                elif node.module == "jax":
                    for alias in node.names:
                        if alias.name == "numpy":
                            self.jax_aliases.add(alias.asname or "numpy")

    # ----------------------------------------------------- api matching
    def api_call_name(self, call: ast.Call) -> Optional[str]:
        """'get' if `call` invokes ray_tpu.get under any alias, etc.;
        None for non-API calls."""
        fn = call.func
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in self.module_aliases and \
                fn.attr in _API_NAMES:
            return fn.attr
        if isinstance(fn, ast.Name):
            return self.api_aliases.get(fn.id)
        return None

    def is_remote_call(self, call: ast.Call) -> bool:
        """True for any `<something>.remote(...)` invocation."""
        return (isinstance(call.func, ast.Attribute)
                and call.func.attr == "remote")

    def jax_rooted(self, node: ast.AST) -> bool:
        """True when `node` is an attribute chain rooted at a jax/jnp
        alias (jnp.dot, jax.jit, jax.numpy.sum, ...)."""
        while isinstance(node, ast.Attribute):
            node = node.value
        return isinstance(node, ast.Name) and node.id in self.jax_aliases

    # ------------------------------------------------------ remote defs
    def _decorator_remote_opts(self, dec: ast.AST) -> Optional[dict]:
        """Options when `dec` is some spelling of the remote decorator:
        @ray_tpu.remote, @remote (imported), @ray_tpu.remote(k=v).
        Returns the kwarg dict ({} for the bare form), else None."""
        call_kwargs = None
        target = dec
        if isinstance(dec, ast.Call):
            target = dec.func
            call_kwargs = {kw.arg: kw.value for kw in dec.keywords
                           if kw.arg is not None}
        if isinstance(target, ast.Attribute):
            if isinstance(target.value, ast.Name) and \
                    target.value.id in self.module_aliases and \
                    target.attr == "remote":
                return call_kwargs or {}
        elif isinstance(target, ast.Name):
            if self.api_aliases.get(target.id) == "remote":
                return call_kwargs or {}
        return None

    def _scan_remote_defs(self):
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                for dec in node.decorator_list:
                    opts = self._decorator_remote_opts(dec)
                    if opts is not None:
                        if isinstance(node, ast.ClassDef):
                            self.remote_classes[node.name] = (node, opts)
                        else:
                            self.remote_functions[node.name] = (node, opts)
                        break
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                # f = ray_tpu.remote(g) / Actor = ray_tpu.remote(Cls)
                if self.api_call_name(node.value) == "remote" and \
                        len(node.value.args) == 1:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            opts = {kw.arg: kw.value
                                    for kw in node.value.keywords
                                    if kw.arg is not None}
                            arg = node.value.args[0]
                            # Class arg (by convention: capitalized name
                            # or a known local class) -> actor class.
                            if isinstance(arg, ast.Name) and \
                                    arg.id[:1].isupper():
                                self.remote_classes[tgt.id] = (node, opts)
                            else:
                                self.remote_functions[tgt.id] = (node,
                                                                 opts)

    # ------------------------------------------------------- scope walk
    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first chain of def nodes containing `node`."""
        out = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = self.parents.get(cur)
        return out

    def in_remote_context(self, node: ast.AST) -> bool:
        """True when `node` executes inside a remote function body or an
        actor-class method (i.e. on a worker, not the driver)."""
        remote_fn_nodes = {n for n, _ in self.remote_functions.values()}
        remote_cls_nodes = {n for n, _ in self.remote_classes.values()}
        cur = self.parents.get(node)
        while cur is not None:
            if cur in remote_fn_nodes:
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                owner = self.parents.get(cur)
                if owner in remote_cls_nodes:
                    return True
            cur = self.parents.get(cur)
        return False


# ================================================================ engine

def _suppressed_by(noqa: Dict[int, Optional[set]], f: Finding) -> bool:
    if f.line not in noqa:
        return False
    codes = noqa[f.line]
    return codes is None or f.code in codes


def _module_pass(source: str, path: str, select: Optional[set]
                 ) -> Tuple[List[Finding], Optional[dict]]:
    """Per-module rules + per-module summaries for the package rules.

    Returns (findings with noqa applied, summary-or-None).  The summary
    is a plain picklable dict: {"path", "noqa", "rules": {code: data}}
    — what a ``--jobs`` worker ships back instead of an AST."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(code="RTL000",
                        message=f"syntax error: {e.msg}", path=path,
                        line=e.lineno or 1, col=e.offset or 0)], None
    ctx = ModuleContext(tree, source, path)
    findings: List[Finding] = []
    for code, cls in sorted(all_rules().items()):
        if select and code not in select:
            continue
        findings.extend(cls().check(ctx))
    findings = [f for f in findings if not ctx.suppressed(f)]
    findings.sort(key=lambda f: (f.line, f.col, f.code))
    summary = {"path": path, "noqa": ctx.noqa, "rules": {}}
    for code, cls in sorted(all_package_rules().items()):
        if select and code not in select:
            continue
        summary["rules"][code] = cls().summarize(ctx)
    return findings, summary


def _package_pass(summaries: Sequence[dict],
                  select: Optional[set] = None) -> List[Finding]:
    """Run every package rule over the merged summaries; per-file noqa
    maps (carried in the summaries) are applied to the results."""
    summaries = [s for s in summaries if s is not None]
    noqa_by_path = {s["path"]: s["noqa"] for s in summaries}
    findings: List[Finding] = []
    for code, cls in sorted(all_package_rules().items()):
        if select and code not in select:
            continue
        per_rule = [s["rules"][code] for s in summaries
                    if code in s["rules"]]
        findings.extend(cls().check_package(per_rule))
    return [f for f in findings
            if not _suppressed_by(noqa_by_path.get(f.path, {}), f)]


def lint_source(source: str, path: str = "<string>",
                select: Optional[set] = None,
                package: bool = True) -> List[Finding]:
    """Lint one module's source; returns findings with noqa applied.
    Package-scope rules run over this single module unless
    ``package=False`` (lint_paths defers them to one whole-tree pass)."""
    findings, summary = _module_pass(source, path, select)
    if package and summary is not None:
        findings = findings + _package_pass([summary], select)
        findings.sort(key=lambda f: (f.line, f.col, f.code))
    return findings


def lint_file(path: str, select: Optional[set] = None,
              package: bool = True) -> List[Finding]:
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            source = f.read()
    except OSError as e:
        return [Finding(code="RTL000", message=f"cannot read: {e}",
                        path=path, line=1, col=0)]
    return lint_source(source, path, select=select, package=package)


def _lint_file_job(args: Tuple[str, Optional[set]]
                   ) -> Tuple[List[Finding], Optional[dict]]:
    """--jobs worker: one file's module pass (pickle-friendly I/O)."""
    path, select = args
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            source = f.read()
    except OSError as e:
        return [Finding(code="RTL000", message=f"cannot read: {e}",
                        path=path, line=1, col=0)], None
    return _module_pass(source, path, select)


_SKIP_DIRS = {".git", "__pycache__", "build", ".eggs", "node_modules"}


def iter_python_files(paths: Sequence[str]) -> Tuple[List[str],
                                                     List[str]]:
    """(python files under `paths`, paths that don't exist).  Missing
    paths are reported, not skipped — a typo'd target must not turn
    the lint gate vacuously green."""
    out: List[str] = []
    missing: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in _SKIP_DIRS
                                 and not d.startswith("."))
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        out.append(os.path.join(root, fname))
        else:
            missing.append(p)
    return out, missing


def lint_paths(paths: Sequence[str],
               select: Optional[set] = None,
               jobs: int = 1) -> List[Finding]:
    """Lint files/dirs.  Module rules run per file (in ``jobs``
    parallel processes when jobs > 1); package rules run ONCE over the
    merged per-module summaries, so the lock-order graph spans every
    module in the invocation."""
    files, missing = iter_python_files(paths)
    findings: List[Finding] = [
        Finding(code="RTL000", message="path does not exist",
                path=p, line=1, col=0) for p in missing]
    summaries: List[Optional[dict]] = []
    if jobs > 1 and len(files) > 1:
        import concurrent.futures as _cf
        _load_rules()
        try:
            with _cf.ProcessPoolExecutor(max_workers=jobs) as pool:
                for f_list, summary in pool.map(
                        _lint_file_job, [(p, select) for p in files],
                        chunksize=8):
                    findings.extend(f_list)
                    summaries.append(summary)
        except (OSError, PermissionError):
            # Sandboxed environments may forbid subprocess spawn;
            # correctness beats parallelism.
            summaries = []
            findings = findings[:len(missing)]
            jobs = 1
    if jobs <= 1 or not summaries:
        summaries = []
        for fpath in files:
            f_list, summary = _lint_file_job((fpath, select))
            findings.extend(f_list)
            summaries.append(summary)
    findings.extend(_package_pass(summaries, select))
    return findings


def collect_summaries(paths: Sequence[str]) -> List[dict]:
    """Per-module package-rule summaries for every file under `paths`
    (the raw material of the RTC102 graph — used by
    ``--emit-lock-graph``)."""
    files, _missing = iter_python_files(paths)
    out: List[dict] = []
    for fpath in files:
        _f, summary = _lint_file_job((fpath, None))
        if summary is not None:
            out.append({"path": summary["path"],
                        **summary["rules"].get("RTC102", {})})
    return out


# ============================================================== baseline
# The baseline maps "relpath::CODE" -> count.  Keys are line-independent
# so unrelated edits don't churn it; a file may carry at most its
# recorded number of findings per code, anything beyond is NEW.

def baseline_key(f: Finding, root: str = ".") -> str:
    rel = os.path.relpath(f.path, root)
    return f"{rel.replace(os.sep, '/')}::{f.code}"


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    counts = data.get("counts", data)
    return {str(k): int(v) for k, v in counts.items()}


def write_baseline(findings: Iterable[Finding], path: str,
                   root: str = ".",
                   preserve: Optional[Dict[str, int]] = None
                   ) -> Dict[str, int]:
    counts: Dict[str, int] = dict(preserve or {})
    for f in findings:
        k = baseline_key(f, root)
        counts[k] = counts.get(k, 0) + 1
    # Keep the per-key justification strings ("reasons") for keys that
    # are still baselined — regeneration must not strip the audit
    # trail of WHY each finding was accepted.
    reasons: Dict[str, str] = {}
    if os.path.exists(path):
        try:
            with open(path, encoding="utf-8") as fh:
                old = json.load(fh)
            reasons = {k: str(v)
                       for k, v in old.get("reasons", {}).items()
                       if k in counts}
        except (OSError, ValueError):
            pass
    payload = {
        "comment": "ray_tpu.lint baseline: pre-existing finding counts "
                   "per file::code; regenerate with --write-baseline",
        "counts": dict(sorted(counts.items())),
    }
    if reasons:
        payload["reasons"] = dict(sorted(reasons.items()))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return counts


def apply_baseline(findings: Sequence[Finding], baseline: Dict[str, int],
                   root: str = ".") -> List[Finding]:
    """Findings NOT covered by the baseline (per-key overflow keeps the
    highest-line hits, so the report points at the newest code)."""
    remaining = dict(baseline)
    new: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        k = baseline_key(f, root)
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            new.append(f)
    return new
